"""Generative serving: slot-based continuous batching over compiled
prefill/decode programs.

The reference has no generative path at all (its tensors are 2-D
batch×features, reference: engine/.../predictors/AverageCombinerUnit.java:47-49);
this is the TPU-native capability the BASELINE Llama configs require.

Design (vLLM-style slots, XLA-flavored):

* a persistent KV cache holds ``n_slots`` independent sequences
  (``models/llama.py::init_slot_cache``), each with its own position;
* **admission** prefills one request's prompt into a free slot — prompts are
  right-padded to a power-of-two bucket so there is one compiled prefill
  program per bucket, never per length;
* **decode** advances ALL active slots one token per device step with a
  single compiled program (static shapes, per-slot position masks) — new
  requests join between steps without stalling in-flight ones;
* sampling happens on device (``sample_tokens``, fused greedy/top-k): only
  ``(S,)`` token ids cross the host boundary per step, never ``(S, vocab)``
  logits;
* **overlapped pipeline** (docs/PERFORMANCE.md): the fused k-step decode
  program returns its final ``(tokens, active, remaining)`` carry as device
  arrays, so in steady state block N+1 dispatches straight from block N's
  on-device carry *before* the host fetches block N's tokens — the host
  consumes results while the chip is already computing the next block, and
  the per-block host round trip vanishes from the critical path.  Any
  host-side state change (admission, deadline reap, disconnect) marks the
  carry dirty and forces one synchronous dispatch rebuilt from host state.

``GenerationScheduler`` is the asyncio front: ``submit(prompt) ->
generated ids``; per-request ``max_new_tokens`` / ``temperature`` /
``eos_id``.  ``GenerativeComponent`` adapts it to the graph-unit contract so
an inference graph can contain a generative node (implementation
``JAX_GENERATIVE``).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import os
import threading
import time
from functools import partial
from typing import Any, AsyncIterator, Callable

import jax
import numpy as np

from seldon_core_tpu import qos
from seldon_core_tpu.graph.units import GraphUnitError, SeldonComponent
from seldon_core_tpu.obs import RECORDER, STAGE_DEVICE_STEP, STAGE_TTFT, TIMELINE
from seldon_core_tpu.obs.metering import METER
from seldon_core_tpu.obs.timeline import (
    EVENT_PREEMPT,
    EVENT_RESUME,
    EVENT_SUSPEND,
)
from seldon_core_tpu.utils.tracectx import current_trace_id
from seldon_core_tpu.parallel.sharding import (
    DEFAULT_RULES,
    ShardingRules,
    shard_params,
)
from seldon_core_tpu.utils.metrics import DEFAULT as DEFAULT_METRICS

log = logging.getLogger(__name__)


def _prefill_buckets(max_seq: int, smallest: int = 16) -> tuple[int, ...]:
    sizes = []
    b = smallest
    while b < max_seq:
        sizes.append(b)
        b *= 2
    sizes.append(max_seq)
    return tuple(sizes)


# placeholder history-seed row for non-speculative prefills: the jitted
# prefill takes the argument either way but never reads it with spec off
_NO_HIST = np.zeros(1, np.int32)


class OutOfKVBlocks(Exception):
    """The paged KV pool cannot reserve the blocks this request needs right
    now; the scheduler holds the request until completions free blocks."""


class GenerativeModel:
    """Compiled slot-cache generation engine for one decoder family.

    Cache buffers are donated to each step, so calls must never interleave;
    an internal lock serializes them (the scheduler already serializes its
    own calls, but warmup may overlap traffic that arrives before /ready).

    ``family_mod`` must expose ``init_slot_cache / prefill_slot /
    decode_slots / sample_tokens`` (``models/llama.py`` does).
    """

    def __init__(
        self,
        cfg: Any,
        params: Any,
        *,
        family_mod: Any = None,
        n_slots: int = 4,
        mesh: Any = None,
        rules: ShardingRules = DEFAULT_RULES,
        param_axes: Any = None,
        dtype: Any = None,
        seq_impl: str = "dense",
        name: str = "generative",
        decode_block: int = 16,
        driver: Any = None,
        kv_block_size: int = 16,
        kv_blocks: int | None = None,
        prefix_reuse: bool | None = None,
        prefix_dram_gb: float | None = None,
        top_k: int = 0,
        spec_draft: int | None = None,
        spec_ngram: int | None = None,
        spec_hist: int = 64,
        spec_method: str | None = None,
        spec_heads: int | None = None,
        spec_heads_path: str | None = None,
        spec_draft_model: str | None = None,
        kv_cache_dtype: str | None = None,
        prefill_chunk: int | None = None,
        decode_kernel: bool | None = None,
        lora_rank: int | None = None,
        lora_slots: int | None = None,
        lora_targets: str | None = None,
        lora_adapters: Any = None,
        conf_signal: bool | None = None,
        embed: bool | None = None,
        memory: Any = None,
    ):
        if family_mod is None:
            from seldon_core_tpu.models import llama as family_mod
        if int(n_slots) < 1:
            # a zero-slot scheduler would park every request forever
            raise GraphUnitError(f"n_slots must be >= 1, got {n_slots}")
        kv_block_size = int(kv_block_size)
        if kv_block_size < 1 or kv_block_size & (kv_block_size - 1):
            raise GraphUnitError(
                f"kv_block_size must be a power of two, got {kv_block_size}"
            )
        if cfg.max_seq % kv_block_size:
            raise GraphUnitError(
                f"max_seq {cfg.max_seq} is not a multiple of kv_block_size "
                f"{kv_block_size}"
            )
        # Multi-host slice: every prefill/decode call is SPMD across the
        # hosts' processes, coordinated through the MultihostDriver (the
        # coordinator leads; engine workers execute the same steps via the
        # follower loop).  Token outputs get replicated so the coordinator
        # reads them locally.
        self._multihost = mesh is not None and any(
            d.process_index != jax.process_index() for d in mesh.devices.flat
        )
        self.driver = driver if self._multihost else None
        if self._multihost and self.driver is None:
            from seldon_core_tpu.executor.multihost import get_driver

            self.driver = get_driver()
            if self.driver is None:
                raise GraphUnitError(
                    f"generative model {name!r}: mesh spans processes but no "
                    "MultihostDriver exists (engine boot initializes it)"
                )
        self.family = family_mod
        self.cfg = cfg
        self.n_slots = int(n_slots)
        self.name = name
        self.mesh = mesh
        # decode steps per device dispatch (the scheduler's block size);
        # 1 disables the scan path entirely
        self.decode_block = max(1, int(decode_block))
        # --- device-side decode frontier (docs/PERFORMANCE.md) ---
        # self-speculative n-gram decoding: draft spec_draft tokens per
        # verify pass from a per-slot on-device history ring; greedy output
        # stays bit-identical to the plain path, accepted tokens cost ~one
        # device step for k tokens.  Opt-in: graph param or SCT_SPEC_DRAFT.
        if spec_draft is None:
            spec_draft = int(os.environ.get("SCT_SPEC_DRAFT", "0") or 0)
        if spec_ngram is None:
            spec_ngram = int(os.environ.get("SCT_SPEC_NGRAM", "3") or 3)
        self.spec_draft = max(0, int(spec_draft))
        self.spec_ngram = max(1, int(spec_ngram))
        self.spec_hist = max(8, int(spec_hist))
        if self.spec_draft and self.decode_block <= 1:
            # the draft/verify/accept loop lives inside the fused k-step
            # program; the single-token step has no verify pass to fuse
            # into.  Loud build-time error — silently dropping speculation
            # here used to ship deployments whose operators believed spec
            # was on while every token paid full price.
            raise GraphUnitError(
                f"generative model {name!r}: spec_draft={self.spec_draft} "
                f"requires decode_block > 1, got decode_block="
                f"{self.decode_block} — the draft/verify/accept loop fuses "
                "into the k-step decode program.  Raise decode_block "
                "(graph param or SCT_DECODE_BLOCK) or unset spec_draft "
                "(graph param or SCT_SPEC_DRAFT)."
            )
        # learned speculation (docs/PERFORMANCE.md §6): the draft source.
        #   ngram — PR 7 self-speculation from the per-slot history ring
        #   heads — Medusa-style multi-token heads over the post-ln_f
        #           hidden, drafted inside the same fused step
        #   draft — a co-resident layer-truncated (or preset) draft model
        #           with its own paged KV, greedily unrolled in-program
        # All three feed the SAME verify/accept pass, so greedy output is
        # bit-identical to spec-off regardless of method — only the
        # acceptance rate differs.
        if spec_method is None:
            spec_method = os.environ.get("SCT_SPEC_METHOD", "") or "ngram"
        spec_method = str(spec_method).lower()
        if spec_method not in ("ngram", "heads", "draft"):
            raise GraphUnitError(
                f"spec_method must be 'ngram', 'heads', or 'draft', got "
                f"{spec_method!r}"
            )
        self.spec_method = spec_method if self.spec_draft else None
        if spec_heads is None:
            spec_heads = int(os.environ.get("SCT_SPEC_HEADS", "0") or 0)
        if spec_heads_path is None:
            spec_heads_path = os.environ.get("SCT_SPEC_HEADS_PATH") or None
        if spec_draft_model is None:
            spec_draft_model = os.environ.get("SCT_SPEC_DRAFT_MODEL") or None
        self.spec_heads = 0
        self.spec_heads_path = None
        self._draft_geom: tuple | None = None
        if self.spec_draft:
            if not hasattr(family_mod, "decode_slots_spec_paged"):
                raise GraphUnitError(
                    f"generative family {family_mod.__name__} has no "
                    "decode_slots_spec_paged; speculative decoding needs the "
                    "fused verify step"
                )
            if self.spec_hist <= self.spec_ngram + self.spec_draft:
                raise GraphUnitError(
                    f"spec_hist {self.spec_hist} must exceed spec_ngram "
                    f"{self.spec_ngram} + spec_draft {self.spec_draft}"
                )
            if self.spec_method == "heads":
                self.spec_heads = max(self.spec_draft, int(spec_heads or 0))
                self.spec_heads_path = spec_heads_path
                if not hasattr(family_mod, "apply_medusa_heads"):
                    raise GraphUnitError(
                        f"generative family {family_mod.__name__} has no "
                        "apply_medusa_heads; spec_method='heads' needs the "
                        "Medusa head block"
                    )
            elif self.spec_method == "draft":
                self._draft_geom = self._parse_draft_model(
                    spec_draft_model, name
                )
        # tokens a slot can emit per fused decode step (verify width)
        self._tps = 1 + self.spec_draft
        # cascade confidence signal (docs/GRAPHS.md): per-step top-2 logit
        # margin computed INSIDE the fused decode programs and fetched WITH
        # the block's tokens, so escalation decisions cost zero extra host
        # syncs.  STATIC (a program-cache key via _program_config):
        # deployments with and without the signal never share a compiled
        # step.  Opt-in via the ``conf_signal`` graph parameter or
        # SCT_CASCADE_CONF_SIGNAL=1.
        if conf_signal is None:
            conf_signal = os.environ.get("SCT_CASCADE_CONF_SIGNAL", "0") == "1"
        self.conf_signal = bool(conf_signal)
        # embeddings path (docs/GRAPHS.md): mean-pooled final hidden states
        # via a pure forward — no KV write, no slot.  The flag only gates
        # warmup compilation of the per-bucket embed programs;
        # embed_dispatch works whenever the family provides embed_pooled.
        # Opt-in via the ``embed`` graph parameter or SCT_EMBED=1.
        if embed is None:
            embed = os.environ.get("SCT_EMBED", "0") == "1"
        self.embed_enabled = bool(embed) and hasattr(family_mod, "embed_pooled")
        # int8 paged-KV quantization: ~2x sequences per HBM byte; opt-in
        # via the kv_cache_dtype graph param or SCT_KV_DTYPE=int8
        if kv_cache_dtype is None:
            kv_cache_dtype = os.environ.get("SCT_KV_DTYPE") or None
        if kv_cache_dtype in ("", "auto", "bf16", "bfloat16", "float32", "fp32"):
            kv_cache_dtype = None  # pool float dtype — the default layout
        if kv_cache_dtype not in (None, "int8"):
            raise GraphUnitError(
                f"kv_cache_dtype must be 'int8' or unset, got {kv_cache_dtype!r}"
            )
        self.kv_dtype: str | None = kv_cache_dtype
        # chunked prefill (Sarathi-style, docs/PERFORMANCE.md §7): split an
        # admission's prompt into fixed-size chunks so the scheduler can
        # interleave one chunk per decode sync point — a long prompt then
        # bounds in-flight streams' inter-token latency by ONE chunk's
        # latency instead of the whole prefill.  Chunk boundaries land on
        # KV-block boundaries (rounded up); each chunk past the first runs
        # the suffix-prefill program over the slot's own already-written
        # blocks, so the written K/V — and the first sampled token — are
        # bit-identical to the monolithic prefill.  Opt-in per deployment
        # via the ``prefill_chunk`` graph parameter or SCT_PREFILL_CHUNK.
        if prefill_chunk is None:
            prefill_chunk = int(os.environ.get("SCT_PREFILL_CHUNK", "0") or 0)
        prefill_chunk = max(0, int(prefill_chunk))
        if prefill_chunk:
            prefill_chunk = min(
                -(-prefill_chunk // kv_block_size) * kv_block_size,
                cfg.max_seq,
            )
            if not hasattr(family_mod, "prefill_suffix_paged"):
                log.warning(
                    "generative model %r: family %s has no "
                    "prefill_suffix_paged; chunked prefill disabled",
                    name, family_mod,
                )
                prefill_chunk = 0
        self.prefill_chunk = prefill_chunk
        # Pallas paged decode-attention kernel (ops/paged_attention.py):
        # fuses block-table gather + int8 dequant + attention over the
        # paged pool inside the compiled decode step.  Single-device only
        # for now — the kernel does not partition over a mesh axis — and
        # interpret-mode on CPU so tier-1 covers it.  Opt-in via the
        # ``decode_kernel`` graph parameter or SCT_DECODE_KERNEL=1.
        if decode_kernel is None:
            decode_kernel = os.environ.get("SCT_DECODE_KERNEL", "0") == "1"
        decode_kernel = bool(decode_kernel)
        if decode_kernel:
            import inspect

            _dsp = getattr(family_mod, "decode_slots_paged", None)
            if _dsp is None or "kernel" not in inspect.signature(
                _dsp
            ).parameters:
                log.warning(
                    "generative model %r: family %s decode has no kernel "
                    "path; Pallas decode kernel disabled", name, family_mod,
                )
                decode_kernel = False
            elif mesh is not None:
                log.warning(
                    "generative model %r: the Pallas decode kernel is "
                    "single-device (no mesh partitioning yet); disabled",
                    name,
                )
                decode_kernel = False
        self.decode_kernel = decode_kernel
        # batched multi-LoRA serving (docs/MULTITENANT.md): a stacked
        # (n_layers, lora_slots, ...) adapter pool in HBM, gathered per
        # generation slot INSIDE the fused prefill/decode programs —
        # serving N fine-tune variants of one base from one compiled step.
        # Row 0 is the reserved null adapter (all zeros): adapter-less
        # requests are bit-identical to a lora-off build.  (lora_rank,
        # lora_slots) are STATIC (program cache keys); which named adapter
        # occupies which row is host bookkeeping (executor/lora.py) so
        # registration/eviction never recompiles mid-traffic.
        if lora_rank is None:
            lora_rank = int(os.environ.get("SCT_LORA_RANK", "0") or 0)
        self.lora_rank = max(0, int(lora_rank))
        if lora_slots is None:
            lora_slots = int(os.environ.get("SCT_LORA_SLOTS", "8") or 8)
        if lora_targets is None:
            lora_targets = os.environ.get("SCT_LORA_TARGETS", "qkvo")
        if self.lora_rank and not hasattr(family_mod, "init_lora_params"):
            log.warning(
                "generative model %r: family %s has no init_lora_params; "
                "multi-LoRA serving disabled", name, family_mod,
            )
            self.lora_rank = 0
        self.lora_slots = max(2, int(lora_slots)) if self.lora_rank else 0
        if self.lora_rank:
            targets = tuple(family_mod.LORA_ATTN_TARGETS)
            lt = str(lora_targets or "qkvo").lower()
            if lt in ("qkvo+mlp", "all", "mlp"):
                targets = targets + tuple(family_mod.LORA_MLP_TARGETS)
            elif lt not in ("qkvo", ""):
                raise GraphUnitError(
                    f"lora_targets must be 'qkvo' or 'qkvo+mlp', got "
                    f"{lora_targets!r}"
                )
            self.lora_targets = targets
        else:
            self.lora_targets = ()

        if dtype is not None:
            import jax.numpy as jnp

            def _cast(p):
                dt = getattr(p, "dtype", None) or np.asarray(p).dtype
                return p.astype(dtype) if jnp.issubdtype(dt, jnp.floating) else p

            params = jax.tree.map(_cast, params)
        if mesh is not None:
            if param_axes is not None:
                params = shard_params(params, mesh, param_axes, rules)
            else:
                from jax.sharding import NamedSharding, PartitionSpec as P

                params = jax.device_put(params, NamedSharding(mesh, P()))
        else:
            params = jax.device_put(params)
        self.params = params

        # stacked LoRA adapter pool: device tensors + host registry.  The
        # pool rides every prefill/decode dispatch as a plain (non-donated)
        # argument like the base params; factors are small (rank r), so it
        # replicates across a mesh rather than sharding.
        self.lora_pool = None
        self._lora = None
        self.lora_bytes = 0
        self._slot_aidx = np.zeros(self.n_slots, np.int32)
        self._slot_salt: dict[int, bytes] = {}
        if self.lora_rank:
            lt = family_mod.init_lora_params(
                cfg, self.lora_slots, self.lora_rank,
                targets=self.lora_targets,
                dtype=dtype if dtype is not None else np.float32,
            )
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                lt = jax.device_put(lt, NamedSharding(mesh, P()))
            else:
                lt = jax.device_put(lt)
            self._lora = lt
            self.lora_bytes = sum(
                int(x.nbytes) for x in jax.tree.leaves(lt)
            )
            from seldon_core_tpu.executor.lora import AdapterPool

            self.lora_pool = AdapterPool(
                self.lora_slots, self.lora_rank,
                writer=self._lora_write, name=name,
            )

        # paged KV pool: block 0 is the reserved garbage sink for inactive
        # slots' fixed-shape writes (models/llama.py decode_slots_paged);
        # default pool still admits every slot at full max_seq, an operator
        # shrinks it (or raises n_slots) to oversubscribe against typical
        # lengths instead of worst-case ones
        self.kv_block_size = kv_block_size
        self.max_blocks_per_slot = cfg.max_seq // kv_block_size
        if kv_blocks is None:
            kv_blocks = 1 + self.n_slots * self.max_blocks_per_slot
        self.kv_blocks = int(kv_blocks)
        min_blocks = 1 + self.max_blocks_per_slot
        if self.kv_blocks < min_blocks:
            raise GraphUnitError(
                f"kv_blocks {self.kv_blocks} cannot hold even one max_seq "
                f"request (+sink); need >= {min_blocks}"
            )
        self._free_blocks: list[int] = list(range(1, self.kv_blocks))
        self._slot_blocks: dict[int, list[int]] = {}
        # KV prefix reuse (cache/prefix.py; docs/CACHING.md): a radix index
        # over token-id prefixes -> ref-counted blocks in this pool, so
        # prompts sharing a prefix (system prompts, few-shot preambles)
        # prefill only their novel suffix.  Opt-in per deployment via the
        # ``kv_prefix_reuse`` graph parameter or SCT_CACHE_PREFIX=1; needs
        # the family to provide the suffix-prefill program.
        if prefix_reuse is None:
            prefix_reuse = os.environ.get("SCT_CACHE_PREFIX", "0") == "1"
        if prefix_reuse and not hasattr(family_mod, "prefill_suffix_paged"):
            log.warning(
                "generative model %r: family %s has no prefill_suffix_paged; "
                "KV prefix reuse disabled", name, family_mod,
            )
            prefix_reuse = False
        self.prefix_index = None
        # host-DRAM prefix tier (cache/tiers.py; docs/CACHING.md "Tiered
        # prefix store"): index evictions demote their blocks into a
        # byte-bounded host store instead of dropping them; a later radix
        # match promotes them back with one fused scatter.  Opt-in via the
        # ``prefix_dram_gb`` graph parameter or SCT_PREFIX_DRAM_GB.
        self.host_store = None
        if prefix_reuse:
            from seldon_core_tpu.cache.prefix import PrefixIndex

            self.prefix_index = PrefixIndex(kv_block_size)
            if prefix_dram_gb is None:
                prefix_dram_gb = float(
                    os.environ.get("SCT_PREFIX_DRAM_GB", "0") or 0
                )
            dram_bytes = int(float(prefix_dram_gb) * (1 << 30))
            if dram_bytes > 0 and self._multihost:
                # demotion needs a coordinator-side device fetch of the
                # victim blocks, which a multi-host slice cannot address
                # (same constraint as export_slot_kv)
                log.warning(
                    "generative model %r: host-DRAM prefix tier is not "
                    "supported on a multi-host slice; disabled", name,
                )
            elif dram_bytes > 0:
                from seldon_core_tpu.cache.tiers import HostPrefixStore

                self.host_store = HostPrefixStore(
                    kv_block_size, dram_bytes, on_bytes=self._note_dram_bytes
                )
        # peer-replica prefix tier bookkeeping: chain-level keys installed
        # from a peer pull that no admission has hit yet (the first hit is
        # credited to the peer tier, later ones to plain HBM), plus the
        # pull/serve counters for the per-tier telemetry
        self._peer_chains: set = set()
        self.peer_hits = 0  # admissions whose prefix came from a peer pull
        self.peer_installs = 0  # chain levels installed from peer pulls
        self.peer_serves = 0  # chains exported to pulling peers
        self.dram_hits = 0  # admissions that promoted >=1 level from DRAM
        # per-slot reuse bookkeeping: the prompt (for index insertion at
        # release) and how many leading blocks were matched (shared refs)
        self._slot_prompt: dict[int, np.ndarray] = {}
        self._slot_matched: dict[int, int] = {}
        # which tier satisfied the slot's prefix match (hbm/dram/peer/none)
        # + how many levels the admission promoted from DRAM — stamped
        # into the timeline admit event via reservation_snapshot
        self._slot_tier: dict[int, str] = {}
        self._slot_promoted: dict[int, int] = {}
        # full table row per reserved slot (shared-prefix blocks included):
        # the disagg KV export reads the slot's prompt blocks through it
        self._slot_row: dict[int, np.ndarray] = {}

        cache_dtype = dtype if dtype is not None else np.float32
        if self.kv_dtype:
            try:
                cache = family_mod.init_paged_cache(
                    cfg, self.n_slots, self.kv_blocks, kv_block_size,
                    dtype=cache_dtype, kv_dtype=self.kv_dtype,
                )
            except TypeError:
                raise GraphUnitError(
                    f"generative family {family_mod.__name__} does not "
                    f"support kv_cache_dtype={self.kv_dtype!r}"
                ) from None
        else:
            cache = family_mod.init_paged_cache(
                cfg, self.n_slots, self.kv_blocks, kv_block_size,
                dtype=cache_dtype,
            )
        if self.spec_draft:
            # per-slot history ring for the on-device n-gram proposer:
            # token at position p lives at hist[slot, p % H]
            import jax.numpy as jnp

            cache["hist"] = jnp.zeros(
                (self.n_slots, self.spec_hist), jnp.int32
            )
        # learned proposer state (docs/PERFORMANCE.md §6).  _spec_ps rides
        # every decode-k dispatch as a plain (non-donated) argument like
        # the base params: the Medusa head block for 'heads', the draft
        # model's weights for 'draft', None for 'ngram'.
        self._spec_ps = None
        self._draft_cfg = None
        self.spec_heads_bytes = 0
        self.draft_weight_bytes = 0
        self.draft_kv_bytes = 0
        if self.spec_method == "heads":
            import jax.numpy as jnp

            if self.spec_heads_path:
                # trained heads from an .npz checkpoint (executor/checkpoint)
                from seldon_core_tpu.executor.checkpoint import load_params

                heads = load_params(self.spec_heads_path)
                w1 = heads.get("w1") if isinstance(heads, dict) else None
                hd = heads.get("head") if isinstance(heads, dict) else None
                if (
                    w1 is None or hd is None
                    or np.shape(w1)[:1] != np.shape(hd)[:1]
                    or np.shape(w1)[0] < self.spec_draft
                    or np.shape(hd)[-1] != cfg.vocab_size
                ):
                    raise GraphUnitError(
                        f"generative model {name!r}: Medusa checkpoint "
                        f"{self.spec_heads_path!r} must hold w1 (K, E, E) + "
                        f"head (K, E, V) with K >= spec_draft="
                        f"{self.spec_draft} and V == {cfg.vocab_size}"
                    )
                self.spec_heads = int(np.shape(w1)[0])
                heads = {
                    "w1": jnp.asarray(w1, cache_dtype),
                    "head": jnp.asarray(hd, cache_dtype),
                }
            else:
                # synthesized from the base lm_head: untrained heads draft
                # "repeat the argmax" — harmless (verify still emits the
                # real tokens) and enough for the pinned-equal matrix
                heads = family_mod.init_medusa_heads(
                    jax.random.PRNGKey(0), cfg, self.spec_heads,
                    base_head=params["head"], dtype=cache_dtype,
                )
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                heads = jax.device_put(heads, NamedSharding(mesh, P()))
            else:
                heads = jax.device_put(heads)
            self._spec_ps = heads
            self.spec_heads_bytes = sum(
                int(x.nbytes) for x in jax.tree.leaves(heads)
            )
            # per-slot post-ln_f hidden of the LAST emitted token — the
            # heads' draft input, refreshed by every prefill/verify pass
            cache["hlast"] = jnp.zeros(
                (self.n_slots, cfg.hidden), cache_dtype
            )
        elif self.spec_method == "draft":
            import dataclasses

            import jax.numpy as jnp

            kind, geo = self._draft_geom
            if kind == "truncate":
                # the target's own first-N layers: sliced layer stacks are
                # fresh (billed) arrays, everything else shared by ref
                dcfg = dataclasses.replace(cfg, n_layers=int(geo))
                dparams = family_mod.truncate_params(params, int(geo))
                self.draft_weight_bytes = sum(
                    int(x.nbytes) for x in jax.tree.leaves(dparams["layers"])
                )
            else:
                from seldon_core_tpu.models.registry import resolve_config

                fam_name = family_mod.__name__.rsplit(".", 1)[-1]
                dcfg = resolve_config(fam_name, geo, max_seq=cfg.max_seq)
                if dcfg.vocab_size != cfg.vocab_size:
                    raise GraphUnitError(
                        f"generative model {name!r}: draft preset {geo!r} "
                        f"vocab {dcfg.vocab_size} != target vocab "
                        f"{cfg.vocab_size}; drafts would index a different "
                        "token space"
                    )
                dparams = family_mod.init_params(
                    jax.random.PRNGKey(0), dcfg,
                )
                if dtype is not None:
                    dparams = jax.tree.map(_cast, dparams)
                if mesh is not None:
                    from jax.sharding import NamedSharding, PartitionSpec as P

                    dparams = jax.device_put(
                        dparams, NamedSharding(mesh, P())
                    )
                else:
                    dparams = jax.device_put(dparams)
                self.draft_weight_bytes = sum(
                    int(x.nbytes) for x in jax.tree.leaves(dparams)
                )
            self._spec_ps = dparams
            self._draft_cfg = dcfg
            # draft paged KV: same pool geometry, STATIC per-slot block
            # ownership — slot i owns [1 + i*mb, 1 + (i+1)*mb), block 0 the
            # sink.  No allocator, no refcounts: zero leaked draft blocks
            # by construction, and drift after import/resume self-heals
            # (the verify pass re-syncs d_pos and the next draft step
            # rewrites the row).
            mbd = dcfg.max_seq // kv_block_size
            d_blocks = 1 + self.n_slots * mbd
            dkv = family_mod.init_paged_cache(
                dcfg, self.n_slots, d_blocks, kv_block_size,
                dtype=cache_dtype,
            )
            cache["d_k"] = dkv["k"]
            cache["d_v"] = dkv["v"]
            cache["d_pos"] = dkv["pos"]
            cache["d_table"] = jnp.asarray(
                1 + np.arange(self.n_slots * mbd, dtype=np.int32).reshape(
                    self.n_slots, mbd
                )
            )
            self.draft_kv_bytes = int(dkv["k"].nbytes) + int(dkv["v"].nbytes)
        if mesh is not None:
            # KV heads ride the tp axis like the attention weights; blocks
            # and rows stay local (decode is latency-, not FLOP-bound)
            from jax.sharding import NamedSharding, PartitionSpec as P

            kv_sh = NamedSharding(mesh, P(None, None, None, "tp", None))
            rep = NamedSharding(mesh, P())
            placed = {
                "k": jax.device_put(cache["k"], kv_sh),
                "v": jax.device_put(cache["v"], kv_sh),
                "pos": jax.device_put(cache["pos"], rep),
                "table": jax.device_put(cache["table"], rep),
            }
            if "k_scale" in cache:
                sc_sh = NamedSharding(mesh, P(None, None, None, "tp"))
                placed["k_scale"] = jax.device_put(cache["k_scale"], sc_sh)
                placed["v_scale"] = jax.device_put(cache["v_scale"], sc_sh)
            if "hist" in cache:
                placed["hist"] = jax.device_put(cache["hist"], rep)
            if "hlast" in cache:
                placed["hlast"] = jax.device_put(cache["hlast"], rep)
            if "d_k" in cache:
                # draft KV shards like the target pool when its head count
                # divides the tp axis (always true for truncate — same
                # heads); odd preset geometries replicate
                tp = int(mesh.shape.get("tp", 1))
                d_sh = (
                    kv_sh
                    if self._draft_cfg.n_kv_heads % max(tp, 1) == 0
                    else rep
                )
                placed["d_k"] = jax.device_put(cache["d_k"], d_sh)
                placed["d_v"] = jax.device_put(cache["d_v"], d_sh)
                placed["d_pos"] = jax.device_put(cache["d_pos"], rep)
                placed["d_table"] = jax.device_put(cache["d_table"], rep)
            cache = placed
        self._cache = cache
        self.prefill_buckets = tuple(
            b for b in _prefill_buckets(cfg.max_seq) if b >= kv_block_size
        ) or (cfg.max_seq,)

        fam = family_mod

        # fused on-device sampling: greedy or top-k, inside the compiled
        # step — the host never sees logits.  top_k is STATIC (one program
        # per value), validated here so a typo fails at build, not in jit.
        self.top_k = int(top_k or 0)
        if self.top_k:
            import inspect

            if "top_k" not in inspect.signature(fam.sample_tokens).parameters:
                raise GraphUnitError(
                    f"generative family {fam.__name__} does not support "
                    "on-device top-k sampling (sample_tokens lacks top_k)"
                )
            import functools

            _sample = functools.partial(fam.sample_tokens, top_k=self.top_k)
        else:
            _sample = fam.sample_tokens

        def _replicate(x):
            """Token outputs replicate across the slice so the coordinator
            can read the full result locally (no-op single-host)."""
            # topology is fixed per process and the program caches are
            # per-instance, so two configs differing in _multihost can
            # never share a compiled program
            # sct: program-key-ok fixed per-process topology
            if not self._multihost:
                return x
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))

        spec_d = self.spec_draft
        spec_n = self.spec_ngram
        spec_H = self.spec_hist
        # STATIC proposer selection (a _program_config member): the three
        # methods are different compiled programs, never shared
        spec_m = self.spec_method
        # _draft_cfg is fully determined by _draft_geom (a _program_config
        # member) plus the base model config — same geometry, same draft
        # sct: program-key-ok _draft_geom pins it
        dcfg = self._draft_cfg
        # static decode-attention implementation choice: the Pallas kernel
        # path when enabled, the XLA gather path otherwise (both ride the
        # program cache keys via _program_config)
        dec_kw = {"kernel": True} if self.decode_kernel else {}
        # cascade confidence: static branch — programs with the signal emit
        # one extra (rows, S) float32 output riding the existing fetch
        conf_on = self.conf_signal

        def _conf_margin(logits):
            """Top-2 logit margin per row: equal to the top-2 LOGPROB
            margin (softmax is shift-invariant), so thresholds written in
            logprob space apply directly.  Runs inside the compiled step —
            the host never sees logits."""
            import jax.numpy as jnp

            top2 = jax.lax.top_k(logits.astype(jnp.float32), 2)[0]
            return top2[..., 0] - top2[..., 1]

        def _prefill(params, tokens, length, slot, blocks, temperature, seed,
                     hist_seed, aid, lora, cache):
            if spec_m == "heads":
                # stash the post-ln_f hidden at the sampled position: the
                # Medusa heads draft from it at the first decode block
                logits, cache, hid = fam.prefill_slot_paged(
                    params, tokens, length, slot, blocks, cache, cfg,
                    mesh=mesh, seq_impl=seq_impl, lora=lora, adapter_id=aid,
                    return_hidden=True,
                )
                cache["hlast"] = cache["hlast"].at[slot].set(
                    hid.astype(cache["hlast"].dtype)
                )
            else:
                logits, cache = fam.prefill_slot_paged(
                    params, tokens, length, slot, blocks, cache, cfg,
                    mesh=mesh, seq_impl=seq_impl, lora=lora, adapter_id=aid,
                )
            key = jax.random.PRNGKey(seed)
            tok = _sample(logits[None], temperature[None], key)[0]
            if spec_d:
                # seed the proposer ring: prompt tail (host-computed) plus
                # the first sampled token at its position's row
                row = hist_seed.at[length % spec_H].set(tok)
                cache["hist"] = cache["hist"].at[slot].set(row)
            return _replicate(tok), cache

        def _decode(window):
            def fn(params, tokens, active, temperature, seed, aid, lora, cache):
                logits, cache = fam.decode_slots_paged(
                    params, tokens, cache, active, cfg, window=window,
                    lora=lora, adapter_ids=aid, **dec_kw,
                )
                key = jax.random.PRNGKey(seed)
                toks = _sample(logits, temperature, key)
                if conf_on:
                    return (
                        _replicate(toks),
                        _replicate(_conf_margin(logits)),
                        cache,
                    )
                return _replicate(toks), cache

            return fn

        def _decode_k(k, window):
            """k decode steps in ONE device dispatch (lax.scan), with
            per-slot eos/budget early exit ON DEVICE.  One host round trip
            per k tokens instead of per token — the difference between 30
            tok/s and real throughput when the chip sits behind a network
            tunnel, and one dispatch overhead instead of k on local chips.

            Returns the per-step ``(k, S)`` tokens/active-mask AND the final
            ``(tokens, active, remaining)`` carry as device arrays: the
            overlapped pipeline feeds the carry straight into the next
            block's dispatch so steady-state decode never waits on a host
            round trip (the carry args are donated — each block consumes
            its predecessor's buffers in place)."""
            from jax import lax
            import jax.numpy as jnp

            def fn(params, tokens, active, temperature, seed, eos, remaining,
                   aid, lora, spec_ps, cache):
                del spec_ps  # uniform decode-k signature; ngram/off use None
                base_key = jax.random.PRNGKey(seed)

                def body(carry, i):
                    tokens, active, remaining, cache = carry
                    # NOTE: no all-inactive early-exit cond here.  A
                    # lax.cond whose false branch returns the carry verbatim
                    # cannot alias the cache buffers of both branches, so
                    # XLA inserts a full cache copy EVERY step — hundreds of
                    # MB of pure overhead per token that dwarfs the FLOPs
                    # the cond occasionally skips (decode is bandwidth-bound;
                    # inactive slots' math is already masked).
                    logits, cache = fam.decode_slots_paged(
                        params, tokens, cache, active, cfg, window=window,
                        lora=lora, adapter_ids=aid, **dec_kw,
                    )
                    key = jax.random.fold_in(base_key, i)
                    toks = _sample(logits, temperature, key)
                    toks = jnp.where(active, toks, tokens)
                    remaining = jnp.where(active, remaining - 1, remaining)
                    done = (toks == eos) | (remaining <= 0)
                    active2 = active & ~done
                    ys = (
                        (toks, active, _conf_margin(logits))
                        if conf_on
                        else (toks, active)
                    )
                    return (toks, active2, remaining, cache), ys

                (tokens, active, remaining, cache), ys = lax.scan(
                    body, (tokens, active, remaining, cache), jnp.arange(k)
                )
                return tuple(_replicate(y) for y in ys) + (
                    _replicate(tokens),
                    _replicate(active),
                    _replicate(remaining),
                    cache,
                )

            return fn

        def _decode_k_spec(k, window):
            """k fused SPECULATIVE verify passes in one device dispatch
            (docs/PERFORMANCE.md): each pass drafts ``spec_draft`` tokens
            — from the slot's on-device history ring (``ngram``), from the
            Medusa head block over the last verified hidden (``heads``), or
            by greedily unrolling the co-resident draft model over its own
            paged KV (``draft``) — scores current + drafts in one batched
            model call, accepts the longest agreeing prefix, and emits
            1..(1+draft) tokens — so accepted tokens cost ~one device step
            apiece-divided-by-acceptance.  Same contract as
            :func:`_decode_k` with ``k * (1 + draft)`` result rows: the
            second output is the per-row EMITTED mask (exactly the role
            the was-active mask plays in the plain block), and the
            ``(tokens, active, remaining)`` carry stays device-resident
            for the overlapped pipeline.  The proposer feeds ONLY the
            draft lanes — row 0 of a pass is bit-identical to the
            non-speculative program's output, so greedy output never
            depends on the method (only the acceptance rate does)."""
            from jax import lax
            import jax.numpy as jnp

            from seldon_core_tpu.executor.speculative import (
                propose_heads,
                propose_ngram,
            )

            L = 1 + spec_d

            def fn(params, tokens, active, temperature, seed, eos, remaining,
                   aid, lora, spec_ps, cache):
                base_key = jax.random.PRNGKey(seed)
                S = tokens.shape[0]
                offs = jnp.arange(L)[None, :]
                slot_col = jnp.arange(S)[:, None]

                def body(carry, i):
                    tokens, active, remaining, cache = carry
                    hist = cache["hist"]
                    pos = cache["pos"]
                    if spec_m == "heads":
                        head_logits = fam.apply_medusa_heads(
                            spec_ps, cache["hlast"]
                        )
                        drafts = propose_heads(head_logits, draft=spec_d)
                    elif spec_m == "draft":
                        # greedy unroll of the co-resident draft model over
                        # its own paged KV (block-granular view of the same
                        # donated cache dict).  Each step writes the row it
                        # consumed, so draft KV rows < d_pos always hold
                        # the TRUE sequence (accepted prefix) — and the
                        # post-verify d_pos re-sync below heals any drift
                        # from imports/resume by letting the next unroll
                        # rewrite from the synced row.
                        dc = {
                            "k": cache["d_k"], "v": cache["d_v"],
                            "pos": cache["d_pos"], "table": cache["d_table"],
                        }

                        def dbody(dcarry, _):
                            cur, dc = dcarry
                            dlogits, dc = fam.decode_slots_paged(
                                spec_ps, cur, dc, active, dcfg,
                                window=window,
                            )
                            nxt = jnp.argmax(dlogits, axis=-1).astype(
                                jnp.int32
                            )
                            return (nxt, dc), nxt

                        (_, dc), drafts_t = lax.scan(
                            dbody, (tokens, dc), None, length=spec_d
                        )
                        drafts = drafts_t.T
                        cache["d_k"], cache["d_v"] = dc["k"], dc["v"]
                        cache["d_pos"] = dc["pos"]
                    else:
                        drafts = propose_ngram(
                            hist, pos, tokens, n=spec_n, draft=spec_d
                        )
                    qtoks = jnp.concatenate([tokens[:, None], drafts], axis=1)
                    # writes past the slot's reserved blocks (drafts beyond
                    # the remaining budget) route to the sink block
                    qvalid = active[:, None] & (offs < remaining[:, None])
                    if spec_m == "heads":
                        logits, cache, hid = fam.decode_slots_spec_paged(
                            params, qtoks, cache, active, qvalid, cfg,
                            window=window, lora=lora, adapter_ids=aid,
                            return_hidden=True, **dec_kw,
                        )
                    else:
                        logits, cache = fam.decode_slots_spec_paged(
                            params, qtoks, cache, active, qvalid, cfg,
                            window=window, lora=lora, adapter_ids=aid,
                            **dec_kw,
                        )
                    key = jax.random.fold_in(base_key, i)
                    V = logits.shape[-1]
                    out = _sample(
                        logits.reshape(S * L, V),
                        jnp.repeat(temperature, L),
                        key,
                    ).reshape(S, L)
                    # accept the longest prefix where the draft agrees with
                    # what the model actually emits
                    agree = (drafts == out[:, :-1]).astype(jnp.int32)
                    n_acc = jnp.cumprod(agree, axis=1).sum(axis=1)
                    base = qvalid & (offs <= n_acc[:, None])
                    eos_here = base & (eos[:, None] >= 0) & (out == eos[:, None])
                    eos_before = (
                        jnp.cumsum(eos_here.astype(jnp.int32), axis=1)
                        - eos_here.astype(jnp.int32)
                    )
                    emitted = base & (eos_before == 0)
                    n_em = emitted.sum(axis=1)
                    last = jnp.maximum(n_em - 1, 0)
                    new_cur = jnp.take_along_axis(out, last[:, None], axis=1)[:, 0]
                    tokens = jnp.where(active, new_cur, tokens)
                    remaining = jnp.where(active, remaining - n_em, remaining)
                    active2 = active & ~eos_here.any(axis=1) & (remaining > 0)
                    # scatter emitted tokens into the history ring (their
                    # positions pos+1 .. pos+n_em) and advance pos
                    widx = (pos[:, None] + 1 + offs) % spec_H
                    old = jnp.take_along_axis(hist, widx, axis=1)
                    cache["hist"] = hist.at[slot_col, widx].set(
                        jnp.where(emitted, out, old)
                    )
                    cache["pos"] = jnp.where(active, pos + n_em, pos)
                    if spec_m == "heads":
                        # next pass drafts from the hidden of the LAST
                        # emitted token — the verify forward already
                        # computed it, so heads drafting stays free of
                        # extra model calls
                        new_h = jnp.take_along_axis(
                            hid, last[:, None, None], axis=1
                        )[:, 0]
                        cache["hlast"] = jnp.where(
                            active[:, None],
                            new_h.astype(cache["hlast"].dtype),
                            cache["hlast"],
                        )
                    elif spec_m == "draft":
                        # re-sync the draft clock to the accepted position:
                        # rows < pos already hold the true sequence, and
                        # the next unroll rewrites row pos with the new
                        # current token — self-healing after any import/
                        # resume drift
                        cache["d_pos"] = jnp.where(
                            active, cache["pos"], cache["d_pos"]
                        )
                    ys = (
                        (out.T, emitted.T, _conf_margin(logits).T)
                        if conf_on
                        else (out.T, emitted.T)
                    )
                    return (tokens, active2, remaining, cache), ys

                (tokens, active, remaining, cache), ys = lax.scan(
                    body, (tokens, active, remaining, cache), jnp.arange(k)
                )
                # (k, L, S) -> (k*L, S): chronological rows, same shape
                # contract the host delivery loop already speaks
                return tuple(_replicate(y.reshape(k * L, S)) for y in ys) + (
                    _replicate(tokens),
                    _replicate(active),
                    _replicate(remaining),
                    cache,
                )

            return fn

        def _prefill_suffix(pw):
            """Suffix-only prefill against a reused KV prefix (one compiled
            program per (suffix bucket, prefix window))."""

            def fn(params, tokens, prefix_len, length, slot, blocks_row,
                   suffix_blocks, temperature, seed, hist_seed, aid, lora,
                   cache):
                if spec_m == "heads":
                    logits, cache, hid = fam.prefill_suffix_paged(
                        params, tokens, prefix_len, length, slot, blocks_row,
                        suffix_blocks, cache, cfg, prefix_window=pw,
                        lora=lora, adapter_id=aid, return_hidden=True,
                    )
                    cache["hlast"] = cache["hlast"].at[slot].set(
                        hid.astype(cache["hlast"].dtype)
                    )
                else:
                    logits, cache = fam.prefill_suffix_paged(
                        params, tokens, prefix_len, length, slot, blocks_row,
                        suffix_blocks, cache, cfg, prefix_window=pw,
                        lora=lora, adapter_id=aid,
                    )
                key = jax.random.PRNGKey(seed)
                tok = _sample(logits[None], temperature[None], key)[0]
                if spec_d:
                    row = hist_seed.at[length % spec_H].set(tok)
                    cache["hist"] = cache["hist"].at[slot].set(row)
                return _replicate(tok), cache

            return fn

        def _draft_prefill(spec_ps, tokens, length, slot, cache):
            """Draft-model prompt prefill (``spec_method='draft'``): write
            the prompt's K/V into the draft pool so block-one drafting
            sees real context instead of zeros.  Output-invisible — only
            ``d_*`` cache keys change, and the verify pass never reads
            them for emission — so a skipped/deferred run costs acceptance,
            never correctness.  One compiled program per prompt bucket."""
            dc = {
                "k": cache["d_k"], "v": cache["d_v"],
                "pos": cache["d_pos"], "table": cache["d_table"],
            }
            _, dc = fam.prefill_slot_paged(
                spec_ps, tokens, length, slot, dc["table"][slot], dc, dcfg,
                mesh=mesh, seq_impl=seq_impl,
            )
            cache["d_k"], cache["d_v"] = dc["k"], dc["v"]
            cache["d_pos"] = dc["pos"]
            cache["d_table"] = dc["table"]
            return cache

        def _embed(params, tokens, length):
            """Pooled-embedding forward (docs/GRAPHS.md): pure — no cache
            argument, nothing donated, no slot consumed.  One compiled
            program per prompt bucket, like prefill."""
            return _replicate(
                fam.embed_pooled(
                    params, tokens, length, cfg, mesh=mesh, seq_impl=seq_impl
                )
            )

        # cache buffers are donated: each step reuses the previous buffers
        # in place instead of holding two live copies of a multi-GB cache
        # (the lora pool arg is NOT donated — it persists across steps
        # like the base params)
        self._prefill = jax.jit(_prefill, donate_argnums=(10,))
        # draft-model prefill: built only for spec_method='draft'; batch-
        # class work a DeviceArbiter can defer (scheduler run loop)
        self._draft_prefill = (
            jax.jit(_draft_prefill, donate_argnums=(4,))
            if self.spec_method == "draft"
            else None
        )
        self._prefill_suffix_factory = _prefill_suffix
        self._prefill_suffix_jit: dict[tuple, Any] = {}
        self._decode_factory = _decode
        self._decode_jit: dict[tuple, Any] = {}  # (window, config) -> step
        self._decode_k_factory = _decode_k_spec if self.spec_draft else _decode_k
        self._decode_k_jit: dict[tuple, Any] = {}  # (k, window, config)
        # pooled-embedding program (POST /embeddings): jitted once, one
        # compile per prompt bucket via shape specialization; the seen-set
        # only drives compile telemetry
        self._embed_jit = jax.jit(_embed)
        self._embed_buckets_seen: set[int] = set()
        # static program configuration folded into every compiled-program
        # cache key: two deployments differing only in sampling/speculation/
        # quantization/chunking/kernel config must NEVER share a compiled
        # step (the audits in tests/test_spec.py + tests/test_chunked.py
        # hold this)
        self._program_config = (
            self.top_k, self.spec_draft, self.spec_ngram, self.spec_hist,
            self.spec_method, self.spec_heads, self._draft_geom,
            self.kv_dtype, self.prefill_chunk, self.decode_kernel,
            self.lora_rank, self.lora_slots, self.conf_signal,
        )
        # overlapped-pipeline state: the last dispatched block's final
        # (tokens, active, remaining) as DEVICE arrays, plus the host-side
        # (temperature, eos) the block ran with — a continue-dispatch feeds
        # these straight back into the next block without a host sync
        self._carry: tuple | None = None
        self._carry_aux: tuple | None = None
        self.overlapped = 0  # blocks dispatched from the on-device carry
        # deferred draft-model prefills (spec_method='draft' + arbiter):
        # batch-class payloads the scheduler drains at sync points instead
        # of running inline at admission
        self._pending_draft_prefill: list[dict] = []
        self.defer_draft_prefill = False
        self.draft_prefills = 0  # draft-pool prompt prefills dispatched
        # host-side per-slot position CEILING (>= true device position; the
        # device may stop early on eos).  Drives the attention-window bucket:
        # decode reads only cache rows [0, window) — the bandwidth bill once
        # contexts are long — so each block attends over the smallest
        # power-of-two covering the live positions (models/llama.py
        # decode_slots docstring has the numbers).
        self._pos_ceiling = np.zeros(self.n_slots, np.int64)
        if self.driver is not None:
            # symmetric SPMD step bodies for the follower loop; the k value
            # rides the payload so any block size stays in lockstep
            self._mh_prefill_key = self.driver.register_unique(
                f"gen:{name}:prefill", self._exec_prefill
            )
            self._mh_prefill_suffix_key = self.driver.register_unique(
                f"gen:{name}:prefill_suffix", self._exec_prefill_suffix
            )
            # draft-model prompt prefill is a driven step too: it writes
            # draft pool state on every process of the slice
            self._mh_draft_prefill_key = self.driver.register_unique(
                f"gen:{name}:draft_prefill", self._exec_draft_prefill
            )
            self._mh_decode_key = self.driver.register_unique(
                f"gen:{name}:decode", self._exec_decode
            )
            self._mh_decode_k_key = self.driver.register_unique(
                f"gen:{name}:decode_k", self._exec_decode_k
            )
            # overlap continue: payload carries only (k, window, seed) —
            # every process feeds its own locally-stored device carry
            self._mh_decode_cont_key = self.driver.register_unique(
                f"gen:{name}:decode_cont", self._exec_decode_cont
            )
            self._mh_embed_key = self.driver.register_unique(
                f"gen:{name}:embed", self._exec_embed
            )
            # reset writes the pos vector with a cross-process sharding —
            # a device_put every process must participate in, so it's a
            # driven step too (warmup calls it; a coordinator-only reset
            # wedges the slice)
            self._mh_reset_key = self.driver.register_unique(
                f"gen:{name}:reset", self._exec_reset
            )
            # disagg KV import writes blocks + pos/table on every process
            # of the slice (payload carries the raw ndarrays), so it is a
            # driven step like prefill/decode
            self._mh_import_key = self.driver.register_unique(
                f"gen:{name}:import", self._exec_import
            )
            # adapter-row installs write device state on every process of
            # the slice (payload carries the factor ndarrays), so they are
            # driven steps like prefill/decode
            self._mh_lora_key = self.driver.register_unique(
                f"gen:{name}:lora", self._exec_lora_load
            )

        # observability
        self.steps = 0
        self.prefills = 0
        self.embeds = 0  # pooled-embedding forwards (docs/GRAPHS.md)
        # per-block confidence stash (cascade routing): the last fetched
        # block's (rows, S) top-2 logit margins, read by the scheduler's
        # delivery loop exactly like last_block_s — None when conf_signal
        # is off, so the fetch path stays sync-free either way
        self.last_conf_seq: np.ndarray | None = None
        self.prefills_reused = 0  # prefills that skipped a reused prefix
        self.prefill_chunks = 0  # chunked-prefill chunk dispatches
        self.imports = 0  # disagg KV handoffs imported into this pool
        # KV/HBM pool ledger (docs/OBSERVABILITY.md "generation forensics"):
        # high-water mark of blocks in use, and the byte classes the HBM
        # budget splits into — served on /stats/breakdown and as the
        # seldon_kv_* gauges so router/autoscaler pressure decisions are
        # debuggable after the fact
        self._blocks_high_water = 0
        self.param_bytes = sum(
            int(np.prod(x.shape)) * x.dtype.itemsize
            for x in jax.tree.leaves(self.params)
        )
        # program-cache telemetry: hits vs compiles across the dict-cached
        # program families (decode, decode_k, suffix-prefill), per-variant
        # compile seconds (warmup-attributed or measured at the first
        # serving call), and a bounded recent-compiles ring — a mid-traffic
        # recompile becomes a program.compile span instead of a mystery
        # latency spike
        self.program_hits = 0
        self.program_compiles = 0
        from collections import deque as _deque

        self._program_events: _deque = _deque(maxlen=64)
        self.warmup_program_seconds: dict[str, float] = {}
        self._in_warmup = False
        # static program-variant tag shared by warmup labels, profiler
        # TraceAnnotations, and compile telemetry (e.g. "[spec4,int8]")
        tag = []
        if self.spec_draft:
            # ngram (the PR 7 default) stays the bare "specN" tag; the
            # learned proposers name themselves + their geometry
            sfx = f"spec{self.spec_draft}"
            if self.spec_method == "heads":
                sfx += f"+heads{self.spec_heads}"
            elif self.spec_method == "draft":
                kind, geo = self._draft_geom
                sfx += f"+draft:{kind}{geo}" if kind == "truncate" \
                    else f"+draft:{geo}"
            tag.append(sfx)
        if self.kv_dtype:
            tag.append(self.kv_dtype)
        if self.prefill_chunk:
            tag.append(f"chunk{self.prefill_chunk}")
        if self.decode_kernel:
            tag.append("kernel")
        if self.lora_rank:
            tag.append(f"lora{self.lora_rank}")
        if self.conf_signal:
            tag.append("conf")
        self.variant_sfx = ("[" + ",".join(tag) + "]") if tag else ""
        # per-slot inter-token latency ledger (fed by the scheduler's
        # delivery loop): bounded ring for the /stats/breakdown percentiles
        # plus the seldon_itl_seconds histogram.  Each sample is one
        # (fetched block, slot) pair's delivery gap divided by the tokens it
        # carried — a prefill-induced decode stall inflates every live
        # slot's sample for that block, which is exactly what TTFT and
        # device-step histograms could not see.
        from collections import deque

        self._itl = deque(maxlen=4096)
        self._m_itl = DEFAULT_METRICS.itl.labels(name)
        # speculative-decoding ledger: tokens emitted vs (slot, verify-pass)
        # pairs — their ratio is accepted_tokens_per_step (> 1.0 means the
        # drafts are paying for themselves)
        self.spec_emitted_tokens = 0
        self.spec_verify_passes = 0
        # per-(bucket, program) compile attribution filled by warmup() and
        # served on GET /stats/warmup
        self.warmup_programs: list[str] = []
        # decode FLOPs ≈ 2·params per token (roofline's estimate) — feeds
        # the MFU gauge from measured step round trips
        self.flops_per_token = 2.0 * sum(
            int(np.prod(x.shape)) for x in jax.tree.leaves(self.params)
        )
        self._m_device_step = DEFAULT_METRICS.device_step.labels(name)
        self._m_mfu = DEFAULT_METRICS.mfu.labels(name)
        DEFAULT_METRICS.kv_slots_per_chip.labels(name).set(
            self.kv_slots_per_chip()
        )
        # RLock: warmup calls admit/step under the same lock
        self._lock = threading.RLock()
        # HBM memory manager (executor/memory.py): admission-time byte
        # reservation for this model's classes — with SCT_HBM_ENFORCE=1 an
        # over-committing SECOND deployment fails at build instead of
        # OOMing the chip mid-traffic (docs/MULTITENANT.md)
        if memory is None:
            from seldon_core_tpu.executor.memory import MEMORY as memory
        self.memory = memory
        self._mem_key = f"{name}:{id(self):x}"
        # host-DRAM byte classes (prefix_dram + suspend_dram): the host
        # ledger's reserve() REPLACES an owner's class dict, so both
        # classes re-reserve together through _note_host_bytes
        self._host_classes: dict[str, int] = {}
        kv_bytes = int(self._cache["k"].nbytes) + int(self._cache["v"].nbytes)
        scale_bytes = (
            int(self._cache["k_scale"].nbytes)
            + int(self._cache["v_scale"].nbytes)
            if "k_scale" in self._cache
            else 0
        )
        # held for the model's lifetime; release_memory() releases both
        # the HBM and host ledgers
        # sct: pairing-ok ownership transfer to release_memory()
        self.memory.reserve(
            self._mem_key,
            {
                "weights": self.param_bytes,
                "kv_pool": kv_bytes,
                "kv_scales": scale_bytes,
                "adapter_pool": self.lora_bytes,
                # learned speculation (docs/MULTITENANT.md "draft-model
                # HBM accounting"): resident head block / draft weights /
                # the draft model's own paged KV pool
                "spec_heads": self.spec_heads_bytes,
                "draft_weights": self.draft_weight_bytes,
                "draft_kv": self.draft_kv_bytes,
            },
        )
        # graph-declared adapters ("name", "name:seed", comma-separated or
        # a list): registered at build so the deployment is ready to serve
        # them the moment readiness flips
        if self.lora_pool is not None and lora_adapters is None:
            lora_adapters = os.environ.get("SCT_LORA_ADAPTERS") or None
        if self.lora_pool is not None and lora_adapters:
            names = (
                [s for s in str(lora_adapters).split(",")]
                if isinstance(lora_adapters, str)
                else list(lora_adapters)
            )
            for ent in names:
                ent = str(ent).strip()
                if not ent:
                    continue
                nm, _, sd = ent.partition(":")
                self.register_adapter(
                    nm.strip(), seed=int(sd) if sd.strip() else None
                )
        # from here on, adapter registrations are dynamic: on a multi-host
        # slice they broadcast as driven steps instead of local writes
        self._built = True

    def _parse_draft_model(self, spec: str | None, name: str) -> tuple:
        """Resolve a ``spec_draft_model`` string into a STATIC geometry
        tuple (a ``_program_config`` member):

        - ``truncate:N`` — LayerSkip-style self-draft from the target's
          own first N layers (shared weights, no second checkpoint)
        - ``truncate:auto`` — N = max(1, n_layers // 8)
        - ``preset:NAME`` — a separate tiny preset of the same family
          (vocab must match the target's; max_seq is forced to it)
        """
        spec = str(spec or "truncate:auto").strip()
        kind, _, arg = spec.partition(":")
        kind = kind.lower()
        if kind == "truncate":
            arg = (arg or "auto").strip().lower()
            if not hasattr(self.family, "truncate_params"):
                raise GraphUnitError(
                    f"generative family {self.family.__name__} has no "
                    "truncate_params; spec_draft_model='truncate:...' needs "
                    "the layer-truncation helper"
                )
            if arg == "auto":
                n = max(1, int(self.cfg.n_layers) // 8)
            else:
                try:
                    n = int(arg)
                except ValueError:
                    raise GraphUnitError(
                        f"generative model {name!r}: bad truncate layer "
                        f"count in spec_draft_model={spec!r}"
                    ) from None
            if not 1 <= n < int(self.cfg.n_layers):
                raise GraphUnitError(
                    f"generative model {name!r}: truncate:{n} must keep "
                    f"1 <= N < n_layers ({self.cfg.n_layers})"
                )
            return ("truncate", n)
        if kind == "preset" and arg.strip():
            return ("preset", arg.strip())
        raise GraphUnitError(
            f"generative model {name!r}: spec_draft_model must be "
            f"'truncate:N', 'truncate:auto', or 'preset:NAME', got {spec!r}"
        )

    def note_itl(self, seconds: float) -> None:
        """One inter-token-latency sample (scheduler delivery loop)."""
        self._itl.append(float(seconds))
        self._m_itl.observe(seconds)

    def _itl_pct(self, q: float) -> float | None:
        if not self._itl:
            return None
        return float(np.percentile(np.asarray(self._itl), q))

    def _note_compile(self, label: str, seconds: float) -> None:
        """Program-cache telemetry for one fresh compile: the bounded
        recent-compiles ring, per-variant seconds, the prometheus counter,
        and — OUTSIDE warmup, where a compile means readiness lied about
        coverage — a ``program.compile`` root span so the latency spike it
        caused is attributable from /stats/spans."""
        seconds = round(seconds, 3)
        self._program_events.append(
            {
                "label": label,
                "seconds": seconds,
                "ts": time.time(),
                "warmup": self._in_warmup,
            }
        )
        self.warmup_program_seconds.setdefault(label, seconds)
        DEFAULT_METRICS.program_compiles.labels(self.name).inc()
        if not self._in_warmup:
            from seldon_core_tpu.utils.tracectx import make_trace_id

            RECORDER.record_span(
                "program.compile",
                trace_id=make_trace_id(),
                parent_id=None,
                start=time.time() - seconds,
                duration_s=seconds,
                service=self.name,
                attrs={"variant": label, "model": self.name},
            )
            log.warning(
                "generative model %r: mid-traffic program compile %s "
                "(%.3fs) — warmup did not cover this variant",
                self.name, label, seconds,
            )

    def _record_step(self, step_s: float, tokens_emitted: int) -> None:
        """Flight-recorder + metrics for one decode dispatch (runs on the
        scheduler's worker thread; all sinks are thread-safe)."""
        RECORDER.record_stage(STAGE_DEVICE_STEP, step_s)
        self._m_device_step.observe(step_s)
        from seldon_core_tpu.obs import record_host_sync

        record_host_sync(self.name)  # sampled tokens materialized on host
        if tokens_emitted and step_s > 0:
            from seldon_core_tpu.executor.batcher import _chip_peak

            peak = _chip_peak()
            if peak:
                self._m_mfu.set(
                    tokens_emitted * self.flops_per_token / step_s / peak
                )

    # ------------------------------------------------- multi-LoRA adapters

    def register_adapter(
        self,
        name: str,
        *,
        seed: int | None = None,
        factors: Any = None,
        scale: float = 0.05,
    ) -> int:
        """Install adapter ``name`` into the stacked pool and return its
        row (docs/MULTITENANT.md).  ``factors`` is the family's per-adapter
        pytree (``lora_adapter_factors`` layout); without one, synthetic
        factors are generated from ``seed`` (default: a stable hash of the
        name, so every replica builds the SAME stand-in deltas).  LRU
        eviction under pressure and :class:`AdapterPoolFull` when every
        row is pinned by in-flight slots."""
        if self.lora_pool is None:
            raise GraphUnitError(
                f"generative model {self.name!r} was built without "
                "multi-LoRA serving (set lora_rank / SCT_LORA_RANK)"
            )
        if factors is None:
            if seed is None:
                import zlib

                seed = zlib.crc32(str(name).encode())
            factors = self.family.lora_adapter_factors(
                jax.random.PRNGKey(int(seed) & 0x7FFFFFFF), self.cfg,
                self.lora_rank, targets=self.lora_targets, scale=scale,
                dtype=self._lora[self.lora_targets[0]]["a"].dtype,
            )
        return self.lora_pool.register(name, factors)

    def _lora_write(self, idx: int, factors: Any) -> None:
        """AdapterPool's device writer: install one adapter's factors into
        pool row ``idx`` on every process of the slice.  Build-time
        registration (graph-declared adapters) runs symmetrically on every
        process from the same spec, so it writes locally; only DYNAMIC
        registrations after build are coordinator-led driven steps."""
        payload = {"idx": int(idx)}
        for t in self.lora_targets:
            payload[f"a:{t}"] = np.asarray(factors[t]["a"])
            payload[f"b:{t}"] = np.asarray(factors[t]["b"])
        if self.driver is not None and getattr(self, "_built", False):
            self.driver.lead(self._mh_lora_key, payload)
        else:
            self._exec_lora_load(payload)

    def _exec_lora_load(self, payload: dict) -> None:
        """Symmetric adapter-row install (runs on every slice process).
        The pool tensors are NOT donated by the step programs, so the
        functional ``.at[].set`` here never races a dispatched block — the
        in-flight block keeps reading the old buffers, the next dispatch
        picks up the new ones."""
        idx = int(payload["idx"])
        with self._lock:
            lt = {}
            for t, fac in self._lora.items():
                a = fac["a"].at[:, idx].set(
                    np.asarray(payload[f"a:{t}"]).astype(fac["a"].dtype)
                )
                b = fac["b"].at[:, idx].set(
                    np.asarray(payload[f"b:{t}"]).astype(fac["b"].dtype)
                )
                if self.mesh is not None:
                    a = jax.device_put(a, fac["a"].sharding)
                    b = jax.device_put(b, fac["b"].sharding)
                lt[t] = {"a": a, "b": b}
            self._lora = lt

    def _aid_vec(self, payload: dict):
        """Per-slot adapter-id vector for a decode dispatch (None with
        LoRA off — the compiled programs then take an empty pytree)."""
        if self._lora is None:
            return None
        aid = payload.get("aid")
        if aid is None:
            return np.zeros(self.n_slots, np.int32)
        return np.asarray(aid, np.int32)

    def _aid_scalar(self, payload: dict):
        if self._lora is None:
            return None
        return np.int32(payload.get("aid", 0))

    def note_adapter_tokens(self, adapter: str, n: int) -> None:
        """Per-adapter served-token ledger (scheduler delivery loop).
        Keyed by NAME, not slot: a request that completed inside the
        delivered block has already released its slot binding."""
        if self.lora_pool is None or not adapter:
            return
        if self.lora_pool.note_tokens_name(adapter, n):
            # cardinality guard: past SCT_METER_ADAPTER_LABELS distinct
            # adapters the label value rolls up into `other` (the pool's
            # own per-name ledger stays exact)
            DEFAULT_METRICS.lora_tokens.labels(
                self.name, DEFAULT_METRICS.adapter_label(adapter)
            ).inc(int(n))

    def slot_adapter(self, slot: int) -> str | None:
        """Resident adapter name bound to ``slot`` (None = base model)."""
        if self.lora_pool is None:
            return None
        return self.lora_pool.name_of(int(self._slot_aidx[int(slot)]))

    def adapters_snapshot(self) -> dict | None:
        """Adapter-pool ledger for ``GET /stats/breakdown`` — also
        refreshes the ``seldon_lora_*`` gauges."""
        if self.lora_pool is None:
            return None
        snap = self.lora_pool.snapshot()
        snap["bytes"] = self.lora_bytes
        m = DEFAULT_METRICS
        m.lora_resident.labels(self.name).set(snap["resident"])
        m.lora_evictions.labels(self.name).set(snap["evictions"])
        m.lora_bytes.labels(self.name).set(self.lora_bytes)
        return snap

    def release_memory(self) -> None:
        """Drop this model's HBM **and host-DRAM** ledger reservations
        (component close).  The host release is unconditional: suspend
        records (docs/PACKING.md) ledger host bytes even on deployments
        with no prefix tier, and a torn-down deployment's DRAM budget
        must return to the pool either way."""
        self.memory.release(self._mem_key)
        from seldon_core_tpu.executor.memory import host_memory

        self._host_classes.clear()
        host_memory().release(self._mem_key)

    def _note_host_bytes(self, cls: str, nbytes: int) -> None:
        """Merge one host-DRAM byte class (``prefix_dram`` /
        ``suspend_dram``) into this model's HOST-ledger reservation.
        ``reserve()`` REPLACES an owner's class dict, so every class this
        model ledgers re-reserves together — a suspend-store update must
        never wipe the prefix tier's bytes, or vice versa."""
        from seldon_core_tpu.executor.memory import host_memory

        self._host_classes[str(cls)] = int(nbytes)
        # reserve() replaces this owner's class dict (idempotent merge);
        # release_memory() drops the whole key
        # sct: pairing-ok ownership transfer to release_memory()
        host_memory().reserve(self._mem_key, dict(self._host_classes))

    def _note_dram_bytes(self, nbytes: int) -> None:
        """HostPrefixStore byte callback: ledger the DRAM tier's live
        bytes in the HOST memory manager (never the HBM one) and refresh
        the gauge.  Runs only at demote/promote/evict time — admission
        sync points, never the decode hot path."""
        self._note_host_bytes("prefix_dram", int(nbytes))
        DEFAULT_METRICS.prefix_tier_bytes.labels(self.name, "dram").set(
            int(nbytes)
        )

    def note_suspend_bytes(self, nbytes: int) -> None:
        """SuspendStore byte callback (docs/PACKING.md): preempted
        whole-slot records park in host DRAM under ``suspend_dram`` —
        same admission-sync-point-only cadence as the prefix tier."""
        self._note_host_bytes("suspend_dram", int(nbytes))

    # ------------------------------------------------------------------ ops

    def fit_bucket(self, n: int) -> int:
        for b in self.prefill_buckets:
            if b >= n:
                return b
        raise GraphUnitError(
            f"prompt length {n} exceeds max_seq {self.cfg.max_seq}"
        )

    def _count_prefill(self, payload: dict, *, reused: bool = False) -> None:
        """Prefill accounting that stays honest under chunking: a chunked
        admission counts ONE logical prefill (on its final chunk) plus one
        ``prefill_chunks`` tick per chunk dispatched; ``prefills_reused``
        only counts admissions whose reservation matched a shared prefix —
        never the suffix-program calls chunking itself issues."""
        ch = payload.get("chunk")
        if ch is None:
            self.prefills += 1
            if reused:
                self.prefills_reused += 1
            return
        self.prefill_chunks += 1
        if ch.get("last"):
            self.prefills += 1
            if ch.get("reused"):
                self.prefills_reused += 1

    def _exec_prefill(self, payload: dict):
        """Symmetric prefill body (runs on every slice process).  The
        TraceAnnotation names the dispatch after its program-cache variant
        label so a /profile/start capture lines up with span names and
        /stats/warmup entries."""
        label = f"prefill:b{int(payload['padded'].shape[1])}{self.variant_sfx}"
        with self._lock:
            with jax.profiler.TraceAnnotation(label):
                tok, self._cache = self._prefill(
                    self.params,
                    payload["padded"],
                    np.int32(payload["length"]),
                    np.int32(payload["slot"]),
                    np.asarray(payload["blocks"], np.int32),
                    np.float32(payload["temperature"]),
                    np.int32(payload["seed"]),
                    np.asarray(
                        payload.get("hist_seed", _NO_HIST), np.int32
                    ),
                    self._aid_scalar(payload),
                    self._lora,
                    self._cache,
                )
            self._count_prefill(payload)
        return tok

    def reserve_blocks(self, slot: int, total_tokens: int) -> np.ndarray:
        """Reserve the physical blocks ``slot`` needs for a request whose
        prompt+generation will reach ``total_tokens``; returns the slot's
        zero-padded table row.  Raises :class:`OutOfKVBlocks` when the pool
        cannot cover it right now (the scheduler queues the request)."""
        row, _ = self.reserve_for_prompt(slot, None, total_tokens)
        return row

    def reserve_for_prompt(
        self,
        slot: int,
        prompt: "np.ndarray | None",
        total_tokens: int,
        adapter: str | None = None,
    ) -> tuple[np.ndarray, int]:
        """Prompt-aware reservation: with prefix reuse enabled, the longest
        chain of full prompt blocks already in the index is REFERENCED
        (shared, immutable) instead of allocated, and only the remainder
        comes from the free pool.  Returns ``(table row, prefix_len)`` —
        ``prefix_len`` tokens of prefill are skipped by the caller.

        ``adapter`` binds the slot to a resident LoRA adapter for the
        request's lifetime (refcounted; released with the slot) AND salts
        the prefix-index keys: LoRA on the attention projections changes
        K/V, so adapter-A blocks must never serve adapter-B — or the base
        model (docs/MULTITENANT.md)."""
        from seldon_core_tpu.cache.prefix import adapter_salt

        aidx = 0
        if adapter:
            if self.lora_pool is None:
                raise GraphUnitError(
                    f"request names adapter {adapter!r} but model "
                    f"{self.name!r} was built without multi-LoRA serving"
                )
            from seldon_core_tpu.executor.lora import UnknownAdapter

            try:
                aidx = self.lora_pool.acquire(adapter)
            except UnknownAdapter as e:
                raise GraphUnitError(str(e)) from None
        salt = adapter_salt(adapter)
        total = min(int(total_tokens), self.cfg.max_seq)
        need = -(-total // self.kv_block_size)
        self.release_slot(slot)  # a stale reservation on this slot is dead
        matched: list[int] = []
        if self.prefix_index is not None and prompt is not None:
            # never reuse the WHOLE prompt: the suffix program needs at
            # least one real token to produce the first sampled logits
            max_reuse = (int(prompt.size) - 1) // self.kv_block_size
            if max_reuse > 0:
                matched = self.prefix_index.match(
                    prompt, min(max_reuse, need), salt=salt
                )
        # DRAM tier lookup: demoted chain levels that EXTEND the HBM match
        # can be promoted back for the price of one fused scatter — they
        # come out of the free pool like owned blocks (and re-enter the
        # index when the slot releases), so the free-pool requirement is
        # unchanged whether or not the promotion happens
        promoted: list[tuple] = []
        if self.host_store is not None and prompt is not None:
            max_reuse = (int(prompt.size) - 1) // self.kv_block_size
            stop = min(max_reuse, need)
            if stop > len(matched):
                promoted = self.host_store.match(
                    prompt, len(matched) + 1, stop, salt=salt
                )
        own_need = need - len(matched)
        if len(self._free_blocks) < own_need and self.prefix_index is not None:
            # reclaim unreferenced index blocks before failing admission
            # (demoting their KV into the host store when the tier is on)
            self._demote_and_free(own_need - len(self._free_blocks))
        if len(self._free_blocks) < own_need:
            if matched:
                self.prefix_index.release(prompt, len(matched), salt=salt)
            if aidx:
                self.lora_pool.release_ref(aidx)
            raise OutOfKVBlocks(
                f"need {own_need} KV blocks, {len(self._free_blocks)} free"
            )
        got = self._free_blocks[-own_need:] if own_need else []
        if own_need:
            del self._free_blocks[-own_need:]
        n_promoted = 0
        if promoted:
            # scatter the demoted levels into the LEADING owned blocks —
            # they hold complete prompt KV, so release_slot's normal
            # insertion absorbs them back into the index at completion
            try:
                self._exec_promote(
                    self._promote_payload(got[: len(promoted)], promoted)
                )
                n_promoted = len(promoted)
                self.host_store.drop([e[0] for e in promoted])
                self.dram_hits += 1
            except Exception:
                # a failed promotion costs only the shortcut: the blocks
                # stay slot-owned and the suffix prefill covers them
                log.warning(
                    "generative model %r: DRAM prefix promotion failed; "
                    "falling back to plain prefill", self.name, exc_info=True,
                )
                n_promoted = 0
        used = (self.kv_blocks - 1) - len(self._free_blocks)
        if used > self._blocks_high_water:
            self._blocks_high_water = used
        self._slot_blocks[slot] = got
        self._slot_aidx[int(slot)] = aidx
        if salt:
            self._slot_salt[int(slot)] = salt
        if self.prefix_index is not None and prompt is not None:
            self._slot_prompt[slot] = np.asarray(prompt, np.int32).copy()
            self._slot_matched[slot] = len(matched)
            self._slot_promoted[slot] = n_promoted
            self._slot_tier[slot] = self._match_tier(
                prompt, len(matched), n_promoted, salt
            )
        row = np.zeros(self.max_blocks_per_slot, np.int32)
        row[: len(matched)] = matched
        row[len(matched):need] = got
        self._slot_row[slot] = row.copy()
        reused = len(matched) + n_promoted
        if reused:
            DEFAULT_METRICS.prefix_tokens_reused.labels(self.name).inc(
                reused * self.kv_block_size
            )
        return row, reused * self.kv_block_size

    def release_slot(self, slot: int) -> None:
        """Return ``slot``'s owned blocks to the pool and drop its shared-
        prefix refs (idempotent).  With prefix reuse on, the completed
        prompt's FULL blocks are absorbed into the index (zero-ref,
        LRU-evictable) instead of freed, so the next shared-prefix prompt
        finds them."""
        slot = int(slot)
        matched = self._slot_matched.pop(slot, 0)
        prompt = self._slot_prompt.pop(slot, None)
        blocks = self._slot_blocks.pop(slot, None)
        salt = self._slot_salt.pop(slot, b"")
        self._slot_tier.pop(slot, None)
        self._slot_promoted.pop(slot, None)
        aidx = int(self._slot_aidx[slot])
        if aidx:
            self._slot_aidx[slot] = 0
            if self.lora_pool is not None:
                self.lora_pool.release_ref(aidx)
        self._slot_row.pop(slot, None)
        if matched and prompt is not None and self.prefix_index is not None:
            self.prefix_index.release(prompt, matched, salt=salt)
        if blocks:
            if self.prefix_index is not None and prompt is not None:
                # owned blocks are table positions [matched, need); the
                # first (full_prompt_blocks - matched) of them hold ONLY
                # complete prompt K/V -> shareable (under the slot's
                # adapter salt — adapter-tagged chains never cross)
                full = int(prompt.size) // self.kv_block_size
                insertable = blocks[: max(0, full - matched)]
                if insertable:
                    rejected = self.prefix_index.insert(
                        prompt, insertable, matched, salt=salt
                    )
                    absorbed = set(insertable) - set(rejected)
                    blocks = [b for b in blocks if b not in absorbed]
            self._free_blocks.extend(blocks)
        if self.prefix_index is not None:
            DEFAULT_METRICS.prefix_blocks.labels(self.name).set(
                len(self.prefix_index)
            )

    @property
    def free_block_count(self) -> int:
        return len(self._free_blocks)

    # -------------------------------------------------- disagg KV handoff

    def export_slot_kv(self, slot: int, prompt_len: int) -> tuple:
        """Fetch the K/V of ``slot``'s prompt blocks to host for a disagg
        handoff (docs/DISAGGREGATION.md): ``(layers, ceil(L/bs), bs,
        kv_heads, head_dim)`` each.  An int8 pool returns a 4-tuple
        ``(k, v, k_scale, v_scale)`` — the QUANTIZED representation plus
        its scales travel verbatim so the import is bit-exact with no
        re-quantization.  The slot's reservation pins the blocks — shared
        prefix blocks included — so nothing here can be reclaimed or
        overwritten until the owner releases the slot, which it only does
        after the handoff succeeds or is abandoned."""
        if self._multihost:
            raise GraphUnitError(
                "disagg KV export is not supported from a multi-host slice "
                "(the coordinator cannot address every shard); run the "
                "prefill pool single-host or serve unified"
            )
        slot = int(slot)
        row = self._slot_row.get(slot)
        if row is None:
            raise GraphUnitError(f"slot {slot} holds no reservation to export")
        nb = -(-int(prompt_len) // self.kv_block_size)
        phys = np.asarray(row[:nb], np.int32)
        with self._lock:
            # once per migrated slot, off the per-token path (DISAGG.md)
            k = np.asarray(  # sct: host-sync-ok handoff export
                jax.device_get(self._cache["k"][:, phys])
            )
            v = np.asarray(  # sct: host-sync-ok handoff export
                jax.device_get(self._cache["v"][:, phys])
            )
            if self.kv_dtype:
                ks = np.asarray(  # sct: host-sync-ok handoff export
                    jax.device_get(self._cache["k_scale"][:, phys])
                )
                vs = np.asarray(  # sct: host-sync-ok handoff export
                    jax.device_get(self._cache["v_scale"][:, phys])
                )
                return k, v, ks, vs
        return k, v

    def export_spec_state(self, slot: int) -> dict | None:
        """Proposer state for a handoff/suspend frame (codec v5): the
        method tag plus, for ``heads``, the slot's Medusa hidden — the one
        piece an importer cannot recompute without a forward pass.  The
        ``draft`` method ships no tensor: the importer re-prefills the
        draft pool from the carried token history and ``d_pos``
        self-heals at the first verify pass.  ``None`` for ngram/off —
        the history ring already travels as the frame's prompt."""
        if not self.spec_method or self.spec_method == "ngram":
            return None
        state: dict = {"method": self.spec_method}
        if self.spec_method == "heads":
            with self._lock:
                # once per migrated slot, off the per-token path
                state["hlast"] = np.asarray(  # sct: host-sync-ok handoff export
                    jax.device_get(self._cache["hlast"][int(slot)])
                )
        return state

    def draft_prefill_dispatch(self, slot: int, prompt: np.ndarray):
        """Prefill the co-resident draft model's paged KV for ``slot``
        (``spec_method='draft'``).  Batch-class work: with a DeviceArbiter
        attached the scheduler defers it to the next sync point
        (:meth:`drain_draft_prefills`) under the draft registrant, so
        interactive verify blocks never queue behind it.  Skipping or
        delaying it costs acceptance only — the verify pass never reads
        draft KV for emission, and ``d_pos`` re-syncs every pass."""
        if self._draft_prefill is None:
            return None
        prompt = np.asarray(prompt, np.int32).ravel()
        L = min(int(prompt.size), self.cfg.max_seq)
        if L < 1:
            return None
        bucket = self.fit_bucket(L)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :L] = prompt[:L]
        payload = {"padded": padded, "length": L, "slot": int(slot)}
        if self.defer_draft_prefill and not self._in_warmup:
            self._pending_draft_prefill.append(payload)
            return None
        if self.driver is not None:
            return self.driver.lead(self._mh_draft_prefill_key, payload)
        return self._exec_draft_prefill(payload)

    def drain_draft_prefills(self) -> int:
        """Run the deferred draft-model prefills (scheduler sync points,
        under the arbiter's batch-class draft registrant)."""
        n = 0
        while self._pending_draft_prefill:
            payload = self._pending_draft_prefill.pop(0)
            if self.driver is not None:
                self.driver.lead(self._mh_draft_prefill_key, payload)
            else:
                self._exec_draft_prefill(payload)
            n += 1
        return n

    def _exec_draft_prefill(self, payload: dict):
        """Symmetric draft-prefill body (runs on every slice process).
        No token output and nothing fetched: a dispatch-only call, so the
        ≤1-host-sync-per-fused-block audit is untouched."""
        label = (
            f"draft_prefill:b{int(payload['padded'].shape[1])}"
            f"{self.variant_sfx}"
        )
        with self._lock:
            with jax.profiler.TraceAnnotation(label):
                self._cache = self._draft_prefill(
                    self._spec_ps,
                    payload["padded"],
                    np.int32(payload["length"]),
                    np.int32(payload["slot"]),
                    self._cache,
                )
            self.draft_prefills += 1
        return None

    def attach_imported(
        self,
        slot: int,
        prompt: np.ndarray,
        k: np.ndarray,
        v: np.ndarray,
        *,
        reserve_tokens: int = 0,
        k_scale: np.ndarray | None = None,
        v_scale: np.ndarray | None = None,
        first_token: int | None = None,
        adapter: str | None = None,
        spec_state: dict | None = None,
    ) -> None:
        """Install another engine's exported prompt KV into ``slot``:
        reserve blocks (longest-prefix reuse applies — blocks this pool
        already holds for the leading prompt blocks are referenced instead
        of rewritten; identical prefixes have bit-identical K/V so skipping
        the write preserves exactness), scatter the novel blocks, and set
        the slot's position/table.  After this the slot decodes exactly as
        if it had prefilled locally.  Int8 pools require the quantized
        blocks plus their ``k_scale``/``v_scale`` (handoff codec v2) and
        scatter both verbatim — bit-exact, no re-quantization.  Raises
        :class:`OutOfKVBlocks` like a local admission when the pool cannot
        cover it."""
        prompt = np.asarray(prompt, np.int32).ravel()
        L = int(prompt.size)
        if L < 1:
            raise GraphUnitError("empty prompt")
        bs = self.kv_block_size
        nb = -(-L // bs)
        k = np.asarray(k)
        v = np.asarray(v)
        expect = (self.cfg.n_layers, nb, bs, self.cfg.n_kv_heads, self.cfg.head_dim)
        if tuple(k.shape) != expect or tuple(v.shape) != expect:
            raise GraphUnitError(
                f"imported KV shape {tuple(k.shape)} does not match this "
                f"pool's {expect} (config or block-size skew)"
            )
        if bool(self.kv_dtype) != (k_scale is not None):
            raise GraphUnitError(
                f"imported KV dtype skew: pool is "
                f"{self.kv_dtype or 'float'} but the handoff "
                f"{'carries' if k_scale is not None else 'lacks'} int8 "
                "scales; pools must share kv_cache_dtype"
            )
        if k_scale is not None:
            k_scale = np.asarray(k_scale)
            v_scale = np.asarray(v_scale)
            if tuple(k_scale.shape) != expect[:4] or tuple(v_scale.shape) != expect[:4]:
                raise GraphUnitError(
                    f"imported KV scale shape {tuple(k_scale.shape)} does "
                    f"not match this pool's {expect[:4]}"
                )
        row, prefix_len = self.reserve_for_prompt(
            slot, prompt, L + max(0, int(reserve_tokens)), adapter=adapter
        )
        skip = prefix_len // bs
        if str(k.dtype) == "bfloat16":
            # frame-safe transport form; _exec_import views it back
            k = k.view(np.uint16)
            v = v.view(np.uint16)
        payload = {
            "slot": int(slot),
            "length": L,
            "row": np.asarray(row, np.int32),
            "phys": np.asarray(row[skip:nb], np.int32),
            "k": np.ascontiguousarray(k[:, skip:]),
            "v": np.ascontiguousarray(v[:, skip:]),
        }
        if k_scale is not None:
            if str(k_scale.dtype) == "bfloat16":
                k_scale = k_scale.view(np.uint16)
                v_scale = v_scale.view(np.uint16)
            payload["k_scale"] = np.ascontiguousarray(k_scale[:, skip:])
            payload["v_scale"] = np.ascontiguousarray(v_scale[:, skip:])
        if self.spec_draft:
            row_h = self._hist_seed(prompt)
            if first_token is not None:
                row_h[L % self.spec_hist] = int(first_token)
            payload["hist_seed"] = row_h
        if self.spec_method == "heads":
            # carried Medusa hidden (handoff codec v5) — or zeros for a
            # pre-v5 frame: the first verify pass refreshes it, so an old
            # frame only costs the FIRST block's acceptance, never output
            hl = (spec_state or {}).get("hlast")
            payload["hlast"] = (
                np.asarray(hl)
                if hl is not None
                else np.zeros(self.cfg.hidden, np.float32)
            )
        if self.driver is not None:
            self.driver.lead(self._mh_import_key, payload)
        else:
            self._exec_import(payload)
        if self.spec_method == "draft":
            # rebuild the draft pool's context from the carried token
            # history: without it the draft proposes from zero context
            # (output-identical, acceptance-poor) until rows refill
            self.draft_prefill_dispatch(slot, prompt)
        self._pos_ceiling[int(slot)] = L
        self.imports += 1

    @staticmethod
    @partial(jax.jit, donate_argnums=(0, 1, 2, 3))
    def _import_scatter(k, v, pos, table, phys, impk, impv, slot, length, row):
        """Donated in-place scatter of imported blocks + slot pos/table —
        one compiled program per novel-block count, no pool copy."""
        k = k.at[:, phys].set(impk.astype(k.dtype))
        v = v.at[:, phys].set(impv.astype(v.dtype))
        pos = pos.at[slot].set(length)
        table = table.at[slot].set(row)
        return k, v, pos, table

    @staticmethod
    @partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
    def _import_scatter_q(
        k, v, ks, vs, pos, table, phys, impk, impv, impks, impvs, slot,
        length, row,
    ):
        """Int8-pool variant: the quantized blocks AND their scales scatter
        verbatim — the handoff's bytes become the pool's bytes."""
        k = k.at[:, phys].set(impk)
        v = v.at[:, phys].set(impv)
        ks = ks.at[:, phys].set(impks.astype(ks.dtype))
        vs = vs.at[:, phys].set(impvs.astype(vs.dtype))
        pos = pos.at[slot].set(length)
        table = table.at[slot].set(row)
        return k, v, ks, vs, pos, table

    @staticmethod
    def _unpack_bf16(arr: np.ndarray, want_dtype) -> np.ndarray:
        if str(want_dtype) == "bfloat16" and arr.dtype == np.uint16:
            import ml_dtypes

            return arr.view(ml_dtypes.bfloat16)
        return arr

    def _exec_import(self, payload: dict) -> None:
        """Symmetric import body (runs on every slice process): scatter the
        imported blocks (+ scales on an int8 pool) and set the slot's
        pos/table (+ proposer history when speculation is on)."""
        import jax.numpy as jnp

        with self._lock:
            c = self._cache
            slot = int(payload["slot"])
            phys = np.asarray(payload["phys"], np.int32)
            newk, newv = c["k"], c["v"]
            newks, newvs = c.get("k_scale"), c.get("v_scale")
            pos, table = c["pos"], c["table"]
            quant = self.kv_dtype is not None
            k = v = ks = vs = None
            if phys.size:
                k = self._unpack_bf16(np.asarray(payload["k"]), newk.dtype)
                v = self._unpack_bf16(np.asarray(payload["v"]), newv.dtype)
                if quant:
                    ks = self._unpack_bf16(
                        np.asarray(payload["k_scale"]), newks.dtype
                    )
                    vs = self._unpack_bf16(
                        np.asarray(payload["v_scale"]), newvs.dtype
                    )
            if phys.size and self.mesh is None:
                # single-device fast path: donated fused scatter (no pool
                # copy; the pool buffers update in place)
                args = (
                    jnp.asarray(phys), jnp.asarray(k), jnp.asarray(v),
                )
                tail = (
                    np.int32(slot), np.int32(payload["length"]),
                    np.asarray(payload["row"], np.int32),
                )
                if quant:
                    (newk, newv, newks, newvs, pos, table) = (
                        GenerativeModel._import_scatter_q(
                            newk, newv, newks, newvs, pos, table,
                            args[0], args[1], args[2],
                            jnp.asarray(ks), jnp.asarray(vs), *tail,
                        )
                    )
                else:
                    newk, newv, pos, table = GenerativeModel._import_scatter(
                        newk, newv, pos, table, *args, *tail
                    )
            else:
                if phys.size:
                    newk = newk.at[:, phys].set(jnp.asarray(k).astype(newk.dtype))
                    newv = newv.at[:, phys].set(jnp.asarray(v).astype(newv.dtype))
                    # the scatter ran outside jit; pin the result back to
                    # the pool's sharding so the donated decode programs
                    # keep their compiled layouts
                    newk = jax.device_put(newk, c["k"].sharding)
                    newv = jax.device_put(newv, c["v"].sharding)
                    if quant:
                        newks = newks.at[:, phys].set(
                            jnp.asarray(ks).astype(newks.dtype)
                        )
                        newvs = newvs.at[:, phys].set(
                            jnp.asarray(vs).astype(newvs.dtype)
                        )
                        newks = jax.device_put(newks, c["k_scale"].sharding)
                        newvs = jax.device_put(newvs, c["v_scale"].sharding)
                pos = pos.at[slot].set(np.int32(payload["length"]))
                table = table.at[slot].set(np.asarray(payload["row"], np.int32))
                if self.mesh is not None:
                    pos = jax.device_put(pos, c["pos"].sharding)
                    table = jax.device_put(table, c["table"].sharding)
            out = dict(c)
            out.update(k=newk, v=newv, pos=pos, table=table)
            if quant:
                out["k_scale"] = newks
                out["v_scale"] = newvs
            if self.spec_draft and "hist_seed" in payload:
                hist = c["hist"].at[int(slot)].set(
                    np.asarray(payload["hist_seed"], np.int32)
                )
                if self.mesh is not None:
                    hist = jax.device_put(hist, c["hist"].sharding)
                out["hist"] = hist
            if "hlast" in payload and "hlast" in c:
                hl = self._unpack_bf16(
                    np.asarray(payload["hlast"]), c["hlast"].dtype
                )
                hlast = c["hlast"].at[int(slot)].set(
                    jnp.asarray(hl).astype(c["hlast"].dtype)
                )
                if self.mesh is not None:
                    hlast = jax.device_put(hlast, c["hlast"].sharding)
                out["hlast"] = hlast
            self._cache = out

    # --------------------------------------------- tiered prefix store
    # (docs/CACHING.md "Tiered prefix store"): demotion catches index
    # evictions into host DRAM; promotion scatters them back; the peer
    # tier exports/installs whole chains across replicas.  Every device
    # touch below happens at a scheduler sync point (reservations and
    # external installs), never inside the fused decode loop, so the
    # ≤1-host-sync-per-block audit holds with tiers on.

    def _demote_and_free(self, shortfall: int) -> None:
        """Evict up to ``shortfall`` blocks' worth of zero-ref prefix
        chains into the free pool, demoting the victims' KV into the
        host-DRAM store first (ONE batched device fetch for the whole
        victim set).  Without the DRAM tier this is plain eviction."""
        if self.prefix_index is None or shortfall <= 0:
            return
        victims = self.prefix_index.evict_entries(shortfall)
        if not victims:
            return
        if self.host_store is not None:
            try:
                phys = np.asarray([b for _k, _d, b in victims], np.int32)
                with self._lock:
                    k = np.asarray(  # sct: host-sync-ok tier demotion
                        jax.device_get(self._cache["k"][:, phys])
                    )
                    v = np.asarray(  # sct: host-sync-ok tier demotion
                        jax.device_get(self._cache["v"][:, phys])
                    )
                    ks = vs = None
                    if self.kv_dtype:
                        ks = np.asarray(  # sct: host-sync-ok tier demotion
                            jax.device_get(self._cache["k_scale"][:, phys])
                        )
                        vs = np.asarray(  # sct: host-sync-ok tier demotion
                            jax.device_get(self._cache["v_scale"][:, phys])
                        )
                # shallowest level first so each chain stays contiguous
                # in the store (a rejected level truncates the chain's
                # tail instead of stranding it)
                order = sorted(
                    range(len(victims)),
                    key=lambda j: (victims[j][0][0], len(victims[j][0][1])),
                )
                rejected: list[tuple] = []
                for i in order:
                    key, depth, _block = victims[i]
                    if any(
                        key[0] == r[0] and key[1].startswith(r[1])
                        for r in rejected
                    ):
                        continue
                    ok = self.host_store.put(
                        key, depth,
                        np.ascontiguousarray(k[:, i]),
                        np.ascontiguousarray(v[:, i]),
                        np.ascontiguousarray(ks[:, i]) if ks is not None else None,
                        np.ascontiguousarray(vs[:, i]) if vs is not None else None,
                    )
                    if not ok:
                        rejected.append(key)
            except Exception:
                log.warning(
                    "generative model %r: DRAM prefix demotion failed; "
                    "dropping %d evicted blocks", self.name, len(victims),
                    exc_info=True,
                )
        self._free_blocks.extend(b for _k, _d, b in victims)

    def _match_tier(
        self, prompt: np.ndarray, n_matched: int, n_promoted: int, salt: bytes
    ) -> str:
        """Which tier satisfied the slot's prefix match: ``peer`` when a
        matched level was installed by a peer pull no admission has used
        yet (the credit is consumed — later hits are plain ``hbm``),
        ``dram`` when levels were promoted from the host store, ``hbm``
        for a plain index match, ``none`` otherwise."""
        if n_matched and self._peer_chains:
            from seldon_core_tpu.cache.tiers import HostPrefixStore

            toks = np.asarray(prompt, np.int32).ravel()
            consumed = False
            for lvl in range(1, n_matched + 1):
                key = HostPrefixStore.level_key(
                    toks, lvl, self.kv_block_size, salt
                )
                if key in self._peer_chains:
                    self._peer_chains.discard(key)
                    consumed = True
            if consumed:
                self.peer_hits += 1
                return "peer"
        if n_promoted:
            return "dram"
        return "hbm" if n_matched else "none"

    def _promote_payload(self, blocks: list, entries: list) -> dict:
        """Stack the store entries' per-block arrays into the scatter
        payload shape ``(layers, n, block_size, kv_heads, head_dim)``."""
        payload = {
            "phys": np.asarray(blocks, np.int32),
            "k": np.ascontiguousarray(np.stack([e[2] for e in entries], 1)),
            "v": np.ascontiguousarray(np.stack([e[3] for e in entries], 1)),
        }
        if self.kv_dtype:
            payload["k_scale"] = np.ascontiguousarray(
                np.stack([e[4] for e in entries], 1)
            )
            payload["v_scale"] = np.ascontiguousarray(
                np.stack([e[5] for e in entries], 1)
            )
        return payload

    @staticmethod
    @partial(jax.jit, donate_argnums=(0, 1))
    def _promote_scatter(k, v, phys, impk, impv):
        """Donated in-place scatter of promoted blocks — no pos/table
        writes (prefill sets those when the slot dispatches)."""
        k = k.at[:, phys].set(impk.astype(k.dtype))
        v = v.at[:, phys].set(impv.astype(v.dtype))
        return k, v

    @staticmethod
    @partial(jax.jit, donate_argnums=(0, 1, 2, 3))
    def _promote_scatter_q(k, v, ks, vs, phys, impk, impv, impks, impvs):
        """Int8-pool variant: quantized blocks AND scales scatter
        verbatim — the store's bytes become the pool's bytes."""
        k = k.at[:, phys].set(impk)
        v = v.at[:, phys].set(impv)
        ks = ks.at[:, phys].set(impks.astype(ks.dtype))
        vs = vs.at[:, phys].set(impvs.astype(vs.dtype))
        return k, v, ks, vs

    def _exec_promote(self, payload: dict) -> None:
        """Scatter promoted/pulled chain blocks into the pool (single
        fused device op; mesh path pins the result back to the pool's
        sharding like :meth:`_exec_import`)."""
        import jax.numpy as jnp

        with self._lock:
            c = self._cache
            phys = np.asarray(payload["phys"], np.int32)
            if not phys.size:
                return
            newk, newv = c["k"], c["v"]
            newks, newvs = c.get("k_scale"), c.get("v_scale")
            quant = self.kv_dtype is not None
            k = self._unpack_bf16(np.asarray(payload["k"]), newk.dtype)
            v = self._unpack_bf16(np.asarray(payload["v"]), newv.dtype)
            ks = vs = None
            if quant:
                ks = self._unpack_bf16(
                    np.asarray(payload["k_scale"]), newks.dtype
                )
                vs = self._unpack_bf16(
                    np.asarray(payload["v_scale"]), newvs.dtype
                )
            if self.mesh is None:
                args = (jnp.asarray(phys), jnp.asarray(k), jnp.asarray(v))
                if quant:
                    newk, newv, newks, newvs = (
                        GenerativeModel._promote_scatter_q(
                            newk, newv, newks, newvs,
                            args[0], args[1], args[2],
                            jnp.asarray(ks), jnp.asarray(vs),
                        )
                    )
                else:
                    newk, newv = GenerativeModel._promote_scatter(
                        newk, newv, *args
                    )
            else:
                newk = newk.at[:, phys].set(jnp.asarray(k).astype(newk.dtype))
                newv = newv.at[:, phys].set(jnp.asarray(v).astype(newv.dtype))
                newk = jax.device_put(newk, c["k"].sharding)
                newv = jax.device_put(newv, c["v"].sharding)
                if quant:
                    newks = newks.at[:, phys].set(
                        jnp.asarray(ks).astype(newks.dtype)
                    )
                    newvs = newvs.at[:, phys].set(
                        jnp.asarray(vs).astype(newvs.dtype)
                    )
                    newks = jax.device_put(newks, c["k_scale"].sharding)
                    newvs = jax.device_put(newvs, c["v_scale"].sharding)
            out = dict(c)
            out.update(k=newk, v=newv)
            if quant:
                out["k_scale"] = newks
                out["v_scale"] = newvs
            self._cache = out

    def export_prefix_kv(
        self,
        tokens: np.ndarray,
        adapter: str | None = None,
        max_blocks: int = 64,
    ) -> tuple | None:
        """Serve a peer's prefix pull: the longest chain this replica
        holds for ``tokens`` (HBM index levels, extended by contiguous
        DRAM-store levels), as ``(depth, k, v, k_scale, v_scale)`` with
        KV shaped ``(layers, depth, block_size, kv_heads, head_dim)``.
        Returns None on no match — including a wrong-adapter probe, whose
        salt never matches the exporting adapter's chains.  HBM levels
        are REF-PINNED for the duration of the device fetch, so a
        concurrent admission's eviction cannot free or demote them
        mid-export."""
        if self._multihost or self.prefix_index is None:
            return None
        from seldon_core_tpu.cache.prefix import adapter_salt

        salt = adapter_salt(adapter)
        tokens = np.asarray(tokens, np.int32).ravel()
        cap = min(
            int(max_blocks),
            int(tokens.size) // self.kv_block_size,
            self.max_blocks_per_slot,
        )
        if cap < 1:
            return None
        k = v = ks = vs = None
        pinned = self.prefix_index.acquire(tokens, cap, salt=salt)
        depth = len(pinned)
        if pinned:
            try:
                phys = np.asarray([b for _k, _d, b in pinned], np.int32)
                with self._lock:
                    k = np.asarray(jax.device_get(self._cache["k"][:, phys]))
                    v = np.asarray(jax.device_get(self._cache["v"][:, phys]))
                    if self.kv_dtype:
                        ks = np.asarray(
                            jax.device_get(self._cache["k_scale"][:, phys])
                        )
                        vs = np.asarray(
                            jax.device_get(self._cache["v_scale"][:, phys])
                        )
            finally:
                self.prefix_index.release(tokens, depth, salt=salt)
        if self.host_store is not None and depth < cap:
            # DRAM levels that contiguously extend the HBM chain ride the
            # same frame — the puller sees one deeper chain
            ext = self.host_store.match(tokens, depth + 1, cap, salt=salt)
            if ext:
                ek = np.stack([e[2] for e in ext], 1)
                ev = np.stack([e[3] for e in ext], 1)
                k = ek if k is None else np.concatenate([k, ek], axis=1)
                v = ev if v is None else np.concatenate([v, ev], axis=1)
                if self.kv_dtype:
                    eks = np.stack([e[4] for e in ext], 1)
                    evs = np.stack([e[5] for e in ext], 1)
                    ks = eks if ks is None else np.concatenate([ks, eks], 1)
                    vs = evs if vs is None else np.concatenate([vs, evs], 1)
                depth += len(ext)
        if not depth:
            return None
        self.peer_serves += 1
        return depth, k, v, ks, vs

    def install_prefix_chain(
        self,
        tokens: np.ndarray,
        k: np.ndarray,
        v: np.ndarray,
        k_scale: "np.ndarray | None" = None,
        v_scale: "np.ndarray | None" = None,
        adapter: str | None = None,
    ) -> int:
        """Install a peer-pulled prefix chain into the pool + index
        (called from the scheduler's sync point, never concurrently with
        an admission).  Only levels deeper than what is already resident
        are installed, as ZERO-REF index entries — evictable like any
        absorbed prompt.  Returns the number of levels installed; any
        failure frees every block it took (zero leaks) and the caller
        falls back to plain prefill."""
        if self.prefix_index is None:
            raise GraphUnitError(
                f"model {self.name!r} has no prefix index to install into"
            )
        if self._multihost:
            raise GraphUnitError(
                "peer prefix install is not supported on a multi-host slice"
            )
        from seldon_core_tpu.cache.prefix import adapter_salt
        from seldon_core_tpu.cache.tiers import HostPrefixStore

        if adapter and (
            self.lora_pool is None or adapter not in self.lora_pool
        ):
            raise GraphUnitError(
                f"pulled chain names adapter {adapter!r} but it is not "
                "resident on this pool"
            )
        tokens = np.asarray(tokens, np.int32).ravel()
        bs = self.kv_block_size
        k = np.asarray(k)
        v = np.asarray(v)
        depth = int(k.shape[1]) if k.ndim == 5 else -1
        expect = (
            self.cfg.n_layers, depth, bs, self.cfg.n_kv_heads,
            self.cfg.head_dim,
        )
        if depth < 1 or tuple(k.shape) != expect or tuple(v.shape) != expect:
            raise GraphUnitError(
                f"pulled chain KV shape {tuple(k.shape)} does not match "
                f"this pool's {expect} (config or block-size skew)"
            )
        if int(tokens.size) < depth * bs:
            raise GraphUnitError("pulled chain tokens do not cover its blocks")
        if bool(self.kv_dtype) != (k_scale is not None):
            raise GraphUnitError(
                f"pulled chain dtype skew: pool is "
                f"{self.kv_dtype or 'float'} but the frame "
                f"{'carries' if k_scale is not None else 'lacks'} int8 "
                "scales"
            )
        salt = adapter_salt(adapter)
        have = self.prefix_index.peek_depth(tokens, depth, salt=salt)
        if have >= depth:
            return 0
        n_new = depth - have
        if len(self._free_blocks) < n_new:
            self._demote_and_free(n_new - len(self._free_blocks))
        if len(self._free_blocks) < n_new:
            return 0  # pool too hot to cache a pull; nothing taken
        got = self._free_blocks[-n_new:]
        del self._free_blocks[-n_new:]
        try:
            payload = {
                "phys": np.asarray(got, np.int32),
                "k": np.ascontiguousarray(k[:, have:]),
                "v": np.ascontiguousarray(v[:, have:]),
            }
            if k_scale is not None:
                payload["k_scale"] = np.ascontiguousarray(
                    np.asarray(k_scale)[:, have:]
                )
                payload["v_scale"] = np.ascontiguousarray(
                    np.asarray(v_scale)[:, have:]
                )
            self._exec_promote(payload)
            rejected = self.prefix_index.insert(tokens, got, have, salt=salt)
        except Exception:
            self._free_blocks.extend(got)
            raise
        if rejected:
            # level raced into the index between peek and insert (no such
            # caller today — installs and admissions share the sync
            # point); the duplicate blocks are unreferenced, free them
            self._free_blocks.extend(rejected)
        absorbed = n_new - len(rejected)
        for lvl in range(have + 1, depth + 1):
            self._peer_chains.add(
                HostPrefixStore.level_key(tokens, lvl, bs, salt)
            )
        self.peer_installs += absorbed
        return absorbed

    def admit_dispatch(
        self,
        slot: int,
        prompt: np.ndarray,
        temperature: float,
        seed: int,
        reserve_tokens: int = 0,
        adapter: str | None = None,
    ):
        """Enqueue one prefill WITHOUT fetching its sampled token (a device
        array is returned).  Several admissions dispatched back-to-back cost
        ONE host round trip when their tokens are fetched together —
        serializing fetch-per-admit costs one RTT each on a tunnel-attached
        chip.  ``reserve_tokens`` sizes the block reservation beyond the
        prompt (the request's max_new_tokens); ``adapter`` binds the slot
        to a resident LoRA adapter for the request's lifetime."""
        prompt = np.asarray(prompt, np.int32).ravel()
        L = prompt.shape[0]
        if L < 1:
            raise GraphUnitError("empty prompt")
        if self.prefill_chunk and L > self.prefill_chunk:
            # chunked admission, dispatched back-to-back (callers that can
            # interleave — the scheduler — use admit_chunk_plan directly
            # and pace one chunk per decode sync point instead)
            plan = self.admit_chunk_plan(
                slot, prompt, temperature, seed, reserve_tokens,
                adapter=adapter,
            )
            tok = None
            for i in range(len(plan["payloads"])):
                tok = self.prefill_chunk_dispatch(plan, i)
            return tok
        blocks_row, prefix_len = self.reserve_for_prompt(
            slot, prompt, L + max(0, int(reserve_tokens)), adapter=adapter
        )
        self._pos_ceiling[int(slot)] = L  # prefill wrote rows [0, L)
        if prefix_len > 0:
            # KV prefix reuse: prefill only the novel suffix; the reused
            # blocks already hold K/V for [0, prefix_len)
            suffix = prompt[prefix_len:]
            bucket = self.fit_bucket(suffix.size)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, : suffix.size] = suffix
            bs = self.kv_block_size
            pb = prefix_len // bs
            lb = bucket // bs
            suffix_blocks = np.zeros(lb, np.int32)
            avail = blocks_row[pb : pb + lb]
            suffix_blocks[: avail.size] = avail  # overflow pads -> sink 0
            payload = {
                "padded": padded,
                "prefix_len": prefix_len,
                "length": L,
                "slot": int(slot),
                "blocks": blocks_row,
                "suffix_blocks": suffix_blocks,
                "window": self._prefix_window(prefix_len),
                "temperature": float(temperature),
                "seed": int(seed),
            }
            if self._lora is not None:
                payload["aid"] = int(self._slot_aidx[int(slot)])
            if self.spec_draft:
                payload["hist_seed"] = self._hist_seed(prompt)
            if self.spec_method == "draft":
                # draft pool has no prefix reuse: it prefills the FULL
                # prompt (the draft model is tiny; correctness is
                # unaffected either way)
                self.draft_prefill_dispatch(slot, prompt)
            if self.driver is not None:
                return self.driver.lead(self._mh_prefill_suffix_key, payload)
            return self._exec_prefill_suffix(payload)
        bucket = self.fit_bucket(L)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :L] = prompt
        payload = {
            "padded": padded,
            "length": L,
            "slot": int(slot),
            "blocks": blocks_row,
            "temperature": float(temperature),
            "seed": int(seed),
        }
        if self._lora is not None:
            payload["aid"] = int(self._slot_aidx[int(slot)])
        if self.spec_draft:
            payload["hist_seed"] = self._hist_seed(prompt)
        if self.spec_method == "draft":
            self.draft_prefill_dispatch(slot, prompt)
        if self.driver is not None:
            return self.driver.lead(self._mh_prefill_key, payload)
        return self._exec_prefill(payload)

    # ------------------------------------------------------ chunked prefill

    def admit_chunk_plan(
        self,
        slot: int,
        prompt: np.ndarray,
        temperature: float,
        seed: int,
        reserve_tokens: int = 0,
        adapter: str | None = None,
    ) -> dict:
        """Reserve ``slot``'s blocks and lay out the admission as a list of
        prefill-chunk payloads (docs/PERFORMANCE.md §7).  Nothing touches
        the device here: the scheduler dispatches one chunk per decode sync
        point via :meth:`prefill_chunk_dispatch`, so a long prompt can
        never stall in-flight streams for more than one chunk's latency.
        KV prefix reuse composes — a matched prefix skips its chunks
        entirely and only the novel suffix is chunked.  The written K/V and
        the first sampled token are bit-identical to the monolithic prefill
        (every chunk past the first is the pinned-equal suffix program over
        the slot's own blocks; the final chunk samples with the admission's
        seed exactly like the monolithic program)."""
        prompt = np.asarray(prompt, np.int32).ravel()
        L = int(prompt.size)
        if L < 1:
            raise GraphUnitError("empty prompt")
        blocks_row, prefix_len = self.reserve_for_prompt(
            slot, prompt, L + max(0, int(reserve_tokens)), adapter=adapter
        )
        self._pos_ceiling[int(slot)] = L
        C = self.prefill_chunk or L
        spans = []
        s = prefix_len
        while s < L:
            e = min(s + C, L)
            spans.append((s, e))
            s = e
        bs = self.kv_block_size
        payloads: list[tuple[str, dict]] = []
        for idx, (s, e) in enumerate(spans):
            seg = prompt[s:e]
            bucket = self.fit_bucket(seg.size)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, : seg.size] = seg
            meta = {
                "i": idx,
                "last": idx == len(spans) - 1,
                "reused": prefix_len > 0,
            }
            if s == 0:
                payloads.append(("prefill", {
                    "padded": padded,
                    "length": int(e),
                    "slot": int(slot),
                    "blocks": blocks_row,
                    "temperature": float(temperature),
                    "seed": int(seed),
                    "chunk": meta,
                }))
            else:
                pb = s // bs
                lb = bucket // bs
                suffix_blocks = np.zeros(lb, np.int32)
                avail = blocks_row[pb : pb + lb]
                suffix_blocks[: avail.size] = avail  # overflow pads -> sink
                payloads.append(("suffix", {
                    "padded": padded,
                    "prefix_len": int(s),
                    "length": int(e),
                    "slot": int(slot),
                    "blocks": blocks_row,
                    "suffix_blocks": suffix_blocks,
                    "window": self._prefix_window(s),
                    "temperature": float(temperature),
                    "seed": int(seed),
                    "chunk": meta,
                }))
            if self._lora is not None:
                payloads[-1][1]["aid"] = int(self._slot_aidx[int(slot)])
            if self.spec_draft:
                payloads[-1][1]["hist_seed"] = self._hist_seed(prompt[:e])
        return {"slot": int(slot), "payloads": payloads,
                "prefix_len": prefix_len,
                "prompt": prompt if self.spec_method == "draft" else None}

    def prefill_chunk_dispatch(self, plan: dict, i: int):
        """Dispatch chunk ``i`` of an :meth:`admit_chunk_plan` admission.
        Returns the chunk's sampled token as a DEVICE array — only the
        final chunk's is the request's real first token; intermediate
        chunks' samples are discarded unfetched, so chunking adds zero host
        syncs over the monolithic path."""
        kind, payload = plan["payloads"][i]
        if i == 0 and self.spec_method == "draft":
            # one full-prompt draft prefill rides the first chunk: the
            # draft model is ~n_layers/8 of the target, so it does not
            # reintroduce the stall chunking removed — and deferring it
            # (arbiter) stays an acceptance-only decision
            self.draft_prefill_dispatch(plan["slot"], plan["prompt"])
        if kind == "prefill":
            if self.driver is not None:
                return self.driver.lead(self._mh_prefill_key, payload)
            return self._exec_prefill(payload)
        if self.driver is not None:
            return self.driver.lead(self._mh_prefill_suffix_key, payload)
        return self._exec_prefill_suffix(payload)

    def _hist_seed(self, prompt: np.ndarray) -> np.ndarray:
        """Host-side proposer-ring row for an admission: the prompt tail at
        its ``p % H`` rows (the first sampled token lands in-program)."""
        from seldon_core_tpu.executor.speculative import seed_history

        return seed_history(prompt, self.spec_hist)

    # ---------------------------------------------- device-frontier stats

    def kv_bytes_per_block(self) -> int:
        """HBM bytes one KV block costs in this pool's layout (scales
        included on an int8 pool) — sizes the HBM tier's byte telemetry."""
        return sum(
            int(self._cache[key].nbytes) // self.kv_blocks
            for key in ("k", "v", "k_scale", "v_scale")
            if key in self._cache
        )

    def kv_bytes_per_slot(self) -> int:
        """HBM bytes one max_seq slot costs in this pool's layout."""
        fam = self.family
        if hasattr(fam, "paged_kv_slot_bytes"):
            dt = str(self._cache["k_scale"].dtype) if self.kv_dtype else str(
                self._cache["k"].dtype
            )
            return int(
                fam.paged_kv_slot_bytes(
                    self.cfg, self.kv_block_size, kv_dtype=self.kv_dtype,
                    dtype=dt,
                )
            )
        per_block = sum(
            int(self._cache[key].nbytes) // self.kv_blocks
            for key in ("k", "v", "k_scale", "v_scale")
            if key in self._cache
        )
        return per_block * self.max_blocks_per_slot

    def kv_slots_per_chip(self, hbm_bytes: int | None = None) -> int:
        """Max-seq sequences this pool layout fits per chip after the
        weights — the capacity number int8 quantization ~doubles.  The HBM
        budget defaults to ``SCT_HBM_GB`` (16 GiB, a v5e chip)."""
        if hbm_bytes is None:
            hbm_bytes = int(
                float(os.environ.get("SCT_HBM_GB", "16")) * (1 << 30)
            )
        return max(
            0, int((hbm_bytes - self.param_bytes) // self.kv_bytes_per_slot())
        )

    def reservation_snapshot(self, slot: int) -> dict | None:
        """Host-side reservation bookkeeping for ``slot`` (None when it
        holds none) — feeds the timeline ledger's admit event with the
        prefix-reuse depth and block split, from values the host already
        holds (no device touch)."""
        slot = int(slot)
        if self._slot_row.get(slot) is None:
            return None
        matched = self._slot_matched.get(slot, 0)
        promoted = self._slot_promoted.get(slot, 0)
        return {
            "blocks_reused": matched,
            "blocks_promoted": promoted,
            "blocks_allocated": len(self._slot_blocks.get(slot, ())),
            "prefix_tokens": (matched + promoted) * self.kv_block_size,
            # which tier satisfied the prefix match (hbm/dram/peer/none)
            "tier": self._slot_tier.get(slot, "none"),
        }

    def pool_snapshot(self) -> dict:
        """The KV/HBM pool ledger (docs/OBSERVABILITY.md): block occupancy
        by holder (free / prefix index / slot reservations), high-water
        mark, byte classes (weights / KV pool / int8 scales), and the
        prefix-index churn counters.  Also refreshes the ``seldon_kv_*``
        gauges — called at /stats/breakdown and /prometheus scrape time,
        never on the decode hot path."""
        total = self.kv_blocks - 1
        free = len(self._free_blocks)
        prefix_held = len(self.prefix_index) if self.prefix_index is not None else 0
        slot_held = sum(len(b) for b in self._slot_blocks.values())
        kv_bytes = int(self._cache["k"].nbytes) + int(self._cache["v"].nbytes)
        scale_bytes = (
            int(self._cache["k_scale"].nbytes) + int(self._cache["v_scale"].nbytes)
            if "k_scale" in self._cache
            else 0
        )
        host_snap = None
        if self.host_store is not None:
            from seldon_core_tpu.executor.memory import host_memory

            host_snap = host_memory().snapshot()
        snap = {
            "blocks": {
                "total": total,
                "free": free,
                "prefix_index": prefix_held,
                "slots": slot_held,
                "high_water": self._blocks_high_water,
                "block_size": self.kv_block_size,
            },
            "bytes": {
                "weights": self.param_bytes,
                "kv_pool": kv_bytes,
                "kv_scales": scale_bytes,
                "adapter_pool": self.lora_bytes,
                "prefix_dram": (
                    self.host_store.bytes if self.host_store is not None else 0
                ),
                "per_slot": self.kv_bytes_per_slot(),
            },
            # chip-level arbitration (executor/memory.py): every resident
            # deployment's classes against the shared HBM budget
            "hbm": self.memory.snapshot(),
            # host-DRAM arbitration for the tiered prefix store
            "host": host_snap,
            "prefix_evictions": (
                self.prefix_index.evicted if self.prefix_index is not None else 0
            ),
            "prefix_insertions": (
                self.prefix_index.inserted if self.prefix_index is not None else 0
            ),
        }
        m = DEFAULT_METRICS
        for state, val in (
            ("free", free),
            ("prefix_index", prefix_held),
            ("slots", slot_held),
        ):
            m.kv_blocks.labels(self.name, state).set(val)
        m.kv_blocks_high_water.labels(self.name).set(self._blocks_high_water)
        for cls, val in (
            ("weights", self.param_bytes),
            ("kv_pool", kv_bytes),
            ("kv_scales", scale_bytes),
            ("adapter_pool", self.lora_bytes),
        ):
            m.kv_bytes.labels(self.name, cls).set(val)
        m.kv_prefix_evictions.labels(self.name).set(snap["prefix_evictions"])
        return snap

    def program_snapshot(self) -> dict:
        """Program-cache telemetry: hits vs fresh compiles across the
        dict-cached program families, per-variant compile seconds (warmup
        or first serving call), and the bounded recent-compiles ring —
        ``warmup: false`` entries are the mid-traffic recompiles that also
        produced a ``program.compile`` span."""
        return {
            "compiles": self.program_compiles,
            "hits": self.program_hits,
            "cached": (
                1  # the monolithic prefill program
                + len(self._decode_jit)
                + len(self._decode_k_jit)
                + len(self._prefill_suffix_jit)
            ),
            "variant_seconds": dict(self.warmup_program_seconds),
            "recent_compiles": list(self._program_events),
        }

    def spec_snapshot(self) -> dict:
        """Device-frontier state for ``GET /stats/breakdown`` and bench:
        speculation acceptance + quantized-pool capacity accounting."""
        ratio = (
            self.spec_emitted_tokens / self.spec_verify_passes
            if self.spec_verify_passes
            else None
        )
        return {
            "spec_draft": self.spec_draft,
            "spec_ngram": self.spec_ngram if self.spec_draft else None,
            "spec_hist": self.spec_hist if self.spec_draft else None,
            # learned speculation (docs/PERFORMANCE.md §6): which proposer
            # this deployment runs + its geometry, and the acceptance
            # ledger keyed by it — one deployment runs ONE proposer, so
            # the per-method split is the labeled ledger itself
            "spec_method": self.spec_method,
            "spec_heads": self.spec_heads or None,
            "spec_draft_model": (
                f"{self._draft_geom[0]}:{self._draft_geom[1]}"
                if self._draft_geom else None
            ),
            "spec_verify_passes": self.spec_verify_passes,
            "spec_emitted_tokens": self.spec_emitted_tokens,
            "accepted_tokens_per_step": (
                round(ratio, 4) if ratio is not None else None
            ),
            "accepted_tokens_per_step_by_method": (
                {
                    self.spec_method: round(ratio, 4),
                }
                if ratio is not None and self.spec_method
                else {}
            ),
            "kv_dtype": self.kv_dtype or str(self._cache["k"].dtype),
            "kv_bytes_per_slot": self.kv_bytes_per_slot(),
            "kv_slots_per_chip": self.kv_slots_per_chip(),
            # chunked prefill + decode kernel state (docs/PERFORMANCE.md §7)
            "prefill_chunk": self.prefill_chunk or None,
            "prefill_chunks": self.prefill_chunks,
            "decode_kernel": self.decode_kernel,
            # batched multi-LoRA (docs/MULTITENANT.md): the adapter-pool
            # ledger — resident/evicted counts, bytes, per-adapter slot
            # occupancy and tokens served
            "lora_rank": self.lora_rank or None,
            "adapters": self.adapters_snapshot(),
            # per-slot inter-token latency (scheduler delivery gaps): the
            # number TTFT/device-step histograms cannot see — a prefill
            # stalling the decode pipeline lands here
            "itl_p50_ms": (
                round(self._itl_pct(50) * 1e3, 3)
                if self._itl else None
            ),
            "itl_p99_ms": (
                round(self._itl_pct(99) * 1e3, 3)
                if self._itl else None
            ),
            "itl_samples": len(self._itl),
            # generation-forensics ledgers (docs/OBSERVABILITY.md): KV/HBM
            # pool occupancy + byte classes, and program-cache churn
            "pool": self.pool_snapshot(),
            "programs": self.program_snapshot(),
            # per-deployment isolation ledgers (docs/PACKING.md): THIS
            # model's rows from the HBM and host-DRAM byte ledgers — on a
            # packed chip they prove byte-level isolation per co-tenant
            "memory": self.memory_snapshot(),
        }

    def memory_snapshot(self) -> dict:
        """This deployment's rows in the chip-wide byte ledgers."""
        from seldon_core_tpu.executor.memory import host_memory

        return {
            "owner": self._mem_key,
            "hbm": self.memory.snapshot()["owners"].get(self._mem_key),
            "host": host_memory().snapshot()["owners"].get(self._mem_key),
        }

    def _prefix_window(self, prefix_len: int) -> int:
        """Smallest power-of-two multiple of the block size covering
        ``prefix_len`` (static per compiled suffix program), capped at
        max_seq."""
        w = self.kv_block_size
        while w < prefix_len:
            w *= 2
        return min(w, self.cfg.max_seq)

    def _exec_prefill_suffix(self, payload: dict):
        """Symmetric suffix-prefill body (runs on every slice process)."""
        bucket = int(payload["padded"].shape[1])
        window = int(payload["window"])
        label = f"suffix:b{bucket}:w{window}{self.variant_sfx}"
        key = (bucket, window) + self._program_config
        fn = self._prefill_suffix_jit.get(key)
        fresh = fn is None
        if fresh:
            fn = jax.jit(
                self._prefill_suffix_factory(window), donate_argnums=(12,)
            )
            self._prefill_suffix_jit[key] = fn
            self.program_compiles += 1
        else:
            self.program_hits += 1
        with self._lock:
            t0 = time.perf_counter()
            with jax.profiler.TraceAnnotation(label):
                tok, self._cache = fn(
                    self.params,
                    payload["padded"],
                    np.int32(payload["prefix_len"]),
                    np.int32(payload["length"]),
                    np.int32(payload["slot"]),
                    np.asarray(payload["blocks"], np.int32),
                    np.asarray(payload["suffix_blocks"], np.int32),
                    np.float32(payload["temperature"]),
                    np.int32(payload["seed"]),
                    np.asarray(
                        payload.get("hist_seed", _NO_HIST), np.int32
                    ),
                    self._aid_scalar(payload),
                    self._lora,
                    self._cache,
                )
            if fresh:
                self._note_compile(label, time.perf_counter() - t0)
            self._count_prefill(payload, reused=True)
        return tok

    def admit(
        self,
        slot: int,
        prompt: np.ndarray,
        temperature: float,
        seed: int,
        reserve_tokens: int = 0,
    ) -> int:
        """Prefill ``prompt`` (1-D int ids) into ``slot``; returns the first
        sampled token."""
        return int(
            self.admit_dispatch(slot, prompt, temperature, seed, reserve_tokens)
        )

    def _exec_embed(self, payload: dict):
        """Pooled-embedding forward body (runs on every slice process)."""
        tokens = np.asarray(payload["padded"], np.int32)
        bucket = int(tokens.shape[1])
        label = f"embed:b{bucket}{self.variant_sfx}"
        fresh = bucket not in self._embed_buckets_seen
        if fresh:
            self._embed_buckets_seen.add(bucket)
            self.program_compiles += 1
        else:
            self.program_hits += 1
        with self._lock:
            t0 = time.perf_counter()
            with jax.profiler.TraceAnnotation(label):
                vec = self._embed_jit(
                    self.params, tokens, np.int32(payload["length"])
                )
            if fresh:
                self._note_compile(label, time.perf_counter() - t0)
            self.embeds += 1
        return vec

    def embed_dispatch(self, prompt: np.ndarray):
        """Enqueue one pooled-embedding forward; returns the (E,) device
        vector WITHOUT fetching (the scheduler batches fetches across the
        embed wave — one sync for N dispatches)."""
        if not hasattr(self.family, "embed_pooled"):
            raise GraphUnitError(
                f"generative family {self.family.__name__} has no "
                "pooled-embedding path"
            )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise GraphUnitError("empty prompt")
        L = int(prompt.size)
        bucket = self.fit_bucket(L)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :L] = prompt
        payload = {"padded": padded, "length": L}
        if self.driver is not None:
            return self.driver.lead(self._mh_embed_key, payload)
        return self._exec_embed(payload)

    def embed(self, prompt: np.ndarray) -> np.ndarray:
        """Fetch one prompt's mean-pooled final hidden state (E,) float32."""
        vec = self.embed_dispatch(prompt)
        # sct: host-sync-ok unbatched embed fetch
        return np.asarray(jax.device_get(vec), np.float32)

    def _window_for(self, active: np.ndarray, extra: int) -> int:
        """Smallest power-of-two cache window covering every ACTIVE slot's
        position ceiling after ``extra`` more tokens (min 64, capped at
        max_seq).  Computed on the coordinator and shipped in the payload so
        every host compiles the same static shape."""
        act = np.asarray(active, bool)
        hi = int(self._pos_ceiling[act].max()) if act.any() else 0
        need = hi + extra + 1
        w = 64
        while w < need:
            w *= 2
        return min(w, self.cfg.max_seq)

    def _exec_decode(self, payload: dict):
        window = int(payload.get("window") or self.cfg.max_seq)
        label = f"decode:w{window}{self.variant_sfx}"
        key = (window,) + self._program_config
        fn = self._decode_jit.get(key)
        fresh = fn is None
        if fresh:
            fn = jax.jit(self._decode_factory(window), donate_argnums=(7,))
            self._decode_jit[key] = fn
            self.program_compiles += 1
        else:
            self.program_hits += 1
        with self._lock:
            t0 = time.perf_counter()
            with jax.profiler.TraceAnnotation(label):
                res = fn(
                    self.params,
                    np.asarray(payload["tokens"], np.int32),
                    np.asarray(payload["active"], bool),
                    np.asarray(payload["temperature"], np.float32),
                    np.int32(payload["seed"]),
                    self._aid_vec(payload),
                    self._lora,
                    self._cache,
                )
            if self.conf_signal:
                toks, conf, self._cache = res
            else:
                toks, self._cache = res
                conf = None
            if fresh:
                self._note_compile(label, time.perf_counter() - t0)
            self.steps += 1
        return (toks, conf) if self.conf_signal else toks

    def step(
        self,
        tokens: np.ndarray,
        active: np.ndarray,
        temperature: np.ndarray,
        seed: int,
        window: int | None = None,
    ) -> np.ndarray:
        """One decode step for all slots -> next token per slot (S,)."""
        payload = {
            "tokens": np.asarray(tokens, np.int32),
            "active": np.asarray(active, bool),
            "temperature": np.asarray(temperature, np.float32),
            "seed": int(seed),
            "window": window or self._window_for(active, 1),
        }
        if self._lora is not None:
            payload["aid"] = self._slot_aidx.copy()
        t0 = time.perf_counter()
        if self.driver is not None:
            res = self.driver.lead(self._mh_decode_key, payload)
        else:
            res = self._exec_decode(payload)
        self._pos_ceiling[np.asarray(active, bool)] += 1
        if self.conf_signal:
            # tokens + confidence margins ride ONE fetch: the single-step
            # audit budget (one sync per step) holds with cascades on
            toks, conf = res
            # sct: host-sync-ok unfused single-step fetch
            out_np, conf_np = jax.device_get((toks, conf))
            # sct: host-sync-ok host copies of the fetch above, no new sync
            out = np.asarray(out_np)
            # sct: host-sync-ok host copy of the fetch above, no new sync
            self.last_conf_seq = np.asarray(conf_np, np.float32)[None]
        else:
            out = np.asarray(  # sct: host-sync-ok unfused single-step fetch
                jax.device_get(res)
            )
            self.last_conf_seq = None
        step_s = time.perf_counter() - t0
        # usage attribution: in single-step mode (decode_block=1) each
        # step IS the fused block, so the meter's token-share split reads
        # the same stash step_k_fetch fills on the fused path
        self.last_block_s = step_s
        self._record_step(step_s, int(np.asarray(active, bool).sum()))
        return out

    def step_k(
        self,
        tokens: np.ndarray,
        active: np.ndarray,
        temperature: np.ndarray,
        seed: int,
        eos: np.ndarray,
        remaining: np.ndarray,
        k: int,
        window: int | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """``k`` decode steps in one dispatch -> ``(k, S)`` sampled tokens
        plus the ``(k, S)`` was-active-at-step mask that says which of them
        are real.  ``eos`` is per-slot (-1 = none), ``remaining`` the
        per-slot token budget — both enforced on device so a slot stops
        consuming cache the step it finishes."""
        return self.step_k_fetch(
            self.step_k_dispatch(
                tokens, active, temperature, seed, eos, remaining, k,
                window=window,
            )
        )

    def step_k_dispatch(
        self,
        tokens: np.ndarray,
        active: np.ndarray,
        temperature: np.ndarray,
        seed: int,
        eos: np.ndarray,
        remaining: np.ndarray,
        k: int,
        window: int | None = None,
    ) -> tuple:
        """Enqueue one k-step decode block WITHOUT fetching its tokens (JAX
        dispatch is async: this returns device arrays immediately).  The
        handle goes to :meth:`step_k_fetch`; between the two the host is
        free to deliver the previous block's tokens — and, in steady state,
        to dispatch the NEXT block from the on-device carry
        (:meth:`step_k_continue`) so the chip never idles on the host."""
        payload = {
            "tokens": np.asarray(tokens, np.int32),
            "active": np.asarray(active, bool),
            "temperature": np.asarray(temperature, np.float32),
            "seed": int(seed),
            "eos": np.asarray(eos, np.int32),
            "remaining": np.asarray(remaining, np.int32),
            "k": int(k),
            # a speculative block can emit up to k * (1 + draft) tokens —
            # the window must cover the ceiling either way
            "window": window or self._window_for(active, k * self._tps),
        }
        if self._lora is not None:
            payload["aid"] = self._slot_aidx.copy()
        t0 = time.perf_counter()
        if self.driver is not None:
            res = self.driver.lead(self._mh_decode_k_key, payload)
        else:
            res = self._exec_decode_k(payload)
        toks_seq, act_seq = res[0], res[1]
        conf_seq = res[2] if len(res) > 2 else None
        act = np.asarray(active, bool)
        self._pos_ceiling[act] += k * self._tps
        return (toks_seq, act_seq, conf_seq, t0, act, int(k))

    def step_k_continue(
        self, active: np.ndarray, seed: int, k: int, window: int | None = None
    ) -> tuple:
        """Dispatch the next k-step block straight from the previous
        block's on-device ``(tokens, active, remaining)`` carry — no host
        round trip touches the critical path.  The caller guarantees no
        host-side state changed since that block was dispatched (no
        admission, no reap, no slot release); eos/budget transitions are
        already device-visible, so a slot that finished mid-block simply
        rides along inactive (its writes go to the sink block)."""
        payload = {
            "k": int(k),
            "seed": int(seed),
            "window": window or self._window_for(active, k * self._tps),
        }
        t0 = time.perf_counter()
        if self.driver is not None:
            res = self.driver.lead(self._mh_decode_cont_key, payload)
        else:
            res = self._exec_decode_cont(payload)
        toks_seq, act_seq = res[0], res[1]
        conf_seq = res[2] if len(res) > 2 else None
        act = np.asarray(active, bool)
        self._pos_ceiling[act] += k * self._tps
        self.overlapped += 1
        return (toks_seq, act_seq, conf_seq, t0, act, int(k))

    def step_k_fetch(self, handle: tuple) -> tuple[np.ndarray, np.ndarray]:
        """Materialize a dispatched block's ``(rows, S)`` tokens + emitted
        mask (``rows = k`` plain, ``k * (1 + spec_draft)`` speculative).
        ONE device_get for both arrays: two separate fetches would pay two
        host round trips per block on a tunnel-attached chip."""
        toks_seq, act_seq, conf_seq, t0, disp_active, k = handle
        # the runtime audit (tests/test_perf.py) budgets exactly one
        # host sync per fused k-block: this is it — confidence margins
        # (conf_signal) ride the SAME fetch, never a second one
        pull = (
            (toks_seq, act_seq, conf_seq)
            if conf_seq is not None
            else (toks_seq, act_seq)
        )
        # sct: host-sync-ok THE one fused-block fetch
        fetched = jax.device_get(pull)
        toks_np, act_np = fetched[0], fetched[1]
        self.last_conf_seq = (
            np.asarray(fetched[2], np.float32) if len(fetched) > 2 else None
        )
        act_np = np.asarray(act_np)
        if self.spec_draft and disp_active is not None and disp_active.any():
            # speculation accounting + ceiling tightening: dispatch assumed
            # the worst case k*(1+d) per slot; the fetched emitted mask says
            # what actually landed.  The ceiling stays an overestimate of
            # the true device position throughout (never an underestimate).
            emitted = act_np.sum(axis=0).astype(np.int64)
            self._pos_ceiling[disp_active] -= (
                k * self._tps - emitted[disp_active]
            )
            # acceptance counts PRODUCTIVE (pass, slot) pairs only — a slot
            # that finished its budget mid-block rides the rest of the
            # fused block inactive in the plain path too, so charging those
            # idle passes would understate what drafting actually bought
            productive = int(
                act_np.reshape(k, self._tps, -1).any(axis=1).sum()
            )
            self.spec_emitted_tokens += int(emitted.sum())
            self.spec_verify_passes += productive
            ratio = self.spec_emitted_tokens / max(1, self.spec_verify_passes)
            DEFAULT_METRICS.spec_emitted.labels(self.name).inc(
                int(emitted.sum())
            )
            DEFAULT_METRICS.spec_verify_passes.labels(self.name).inc(
                productive
            )
            DEFAULT_METRICS.spec_accepted_per_step.labels(self.name).set(ratio)
            # per-proposer split (ngram/heads/draft) of the same ledger
            method = self.spec_method or "ngram"
            DEFAULT_METRICS.spec_emitted_by_method.labels(
                self.name, method
            ).inc(int(emitted.sum()))
            DEFAULT_METRICS.spec_verify_passes_by_method.labels(
                self.name, method
            ).inc(productive)
            DEFAULT_METRICS.spec_accepted_per_step_by_method.labels(
                self.name, method
            ).set(ratio)
        step_s = time.perf_counter() - t0
        # stashed for the delivery loop's usage attribution: this block's
        # measured device seconds get split across the slots it served by
        # token share (obs/metering.py) — host bookkeeping at the one sync
        self.last_block_s = step_s
        self._record_step(step_s, int(act_np.sum()))
        return np.asarray(toks_np), act_np

    def _decode_k_fn(self, k: int, window: int) -> tuple[Any, bool]:
        # static sampling/speculation/quantization config rides the key so
        # no two configurations can ever share a compiled block program
        key = (k, window) + self._program_config
        fn = self._decode_k_jit.get(key)
        if fn is None:
            # donate the carry args (tokens/active/remaining) along with the
            # cache: each block consumes its predecessor's buffers in place,
            # so the overlapped pipeline holds one live carry, not two
            fn = jax.jit(
                self._decode_k_factory(k, window),
                donate_argnums=(1, 2, 6, 10),
            )
            self._decode_k_jit[key] = fn
            self.program_compiles += 1
            return fn, True
        self.program_hits += 1
        return fn, False

    def _exec_decode_k(self, payload: dict):
        k = int(payload["k"])
        window = int(payload.get("window") or self.cfg.max_seq)
        fn, fresh = self._decode_k_fn(k, window)
        label = f"decode_k:k{k}:w{window}{self.variant_sfx}"
        with self._lock:
            temps = np.asarray(payload["temperature"], np.float32)
            eos = np.asarray(payload["eos"], np.int32)
            aid = self._aid_vec(payload)
            t0 = time.perf_counter()
            with jax.profiler.TraceAnnotation(label):
                res = fn(
                    self.params,
                    np.asarray(payload["tokens"], np.int32),
                    np.asarray(payload["active"], bool),
                    temps,
                    np.int32(payload["seed"]),
                    eos,
                    np.asarray(payload["remaining"], np.int32),
                    aid,
                    self._lora,
                    self._spec_ps,
                    self._cache,
                )
            if self.conf_signal:
                (toks_seq, act_seq, conf_seq,
                 tok_c, act_c, rem_c, self._cache) = res
            else:
                (toks_seq, act_seq, tok_c, act_c, rem_c, self._cache) = res
                conf_seq = None
            if fresh:
                self._note_compile(label, time.perf_counter() - t0)
            self._carry = (tok_c, act_c, rem_c)
            # adapter bindings only change at sync points (admission /
            # release), so the continue path reuses the dispatched ids
            self._carry_aux = (temps, eos, aid)
            self.steps += k
        if self.conf_signal:
            return toks_seq, act_seq, conf_seq
        return toks_seq, act_seq

    def _exec_decode_cont(self, payload: dict):
        """Symmetric continue body (runs on every slice process): the next
        block's inputs are THIS process's stored device carry."""
        k = int(payload["k"])
        window = int(payload.get("window") or self.cfg.max_seq)
        fn, fresh = self._decode_k_fn(k, window)
        label = f"decode_k:k{k}:w{window}{self.variant_sfx}"
        with self._lock:
            if self._carry is None or self._carry_aux is None:
                raise RuntimeError(
                    f"generative model {self.name!r}: decode continue "
                    "without a carried block"
                )
            tok_c, act_c, rem_c = self._carry
            temps, eos, aid = self._carry_aux
            t0 = time.perf_counter()
            with jax.profiler.TraceAnnotation(label):
                res = fn(
                    self.params,
                    tok_c,
                    act_c,
                    temps,
                    np.int32(payload["seed"]),
                    eos,
                    rem_c,
                    aid,
                    self._lora,
                    self._spec_ps,
                    self._cache,
                )
            if self.conf_signal:
                (toks_seq, act_seq, conf_seq,
                 tok_c, act_c, rem_c, self._cache) = res
            else:
                (toks_seq, act_seq, tok_c, act_c, rem_c, self._cache) = res
                conf_seq = None
            if fresh:
                self._note_compile(label, time.perf_counter() - t0)
            self._carry = (tok_c, act_c, rem_c)
            self.steps += k
        if self.conf_signal:
            return toks_seq, act_seq, conf_seq
        return toks_seq, act_seq

    def warmup(self) -> int:
        """Compile the decode program and every prefill bucket.

        Held under the model lock end-to-end: traffic that sneaks in before
        readiness flips serializes against the warmup compiles instead of
        racing the donated cache buffers.  If any request already touched the
        cache (traffic hit an unready pod directly), warmup no-ops — it works
        through slot 0 and a position reset, which would corrupt an in-flight
        generation; the programs compile organically in that case.
        """
        with self._lock:
            if self.prefills or self.steps:
                return 0
            n = 0
            self.warmup_programs = []
            # program-variant tag: the static config each compiled program
            # bakes in — /stats/warmup shows it so readiness demonstrably
            # covered the speculative-verify and int8 variants actually
            # served (not just their plain-path namesakes).  Compiles in
            # here are warmup-attributed (no program.compile span); their
            # per-variant seconds land in warmup_program_seconds for the
            # program-cache telemetry to join.
            self._in_warmup = True
            sfx = self.variant_sfx
            # with chunking on, an admission longer than one chunk compiles
            # the chunk-0 bucket plus suffix programs per chunk boundary
            # window — exactly the serving set; the variant list names them
            # so readiness provably covered the chunk pipeline
            suffix_before = set(self._prefill_suffix_jit)
            for b in self.prefill_buckets:
                t0 = time.perf_counter()
                self.admit(0, np.ones(b, np.int32), 0.0, 0)
                if not self.prefill_chunk or b <= self.prefill_chunk:
                    # monolithic program for this bucket really compiled
                    # (longer admissions run the chunk pipeline instead)
                    self.warmup_programs.append(f"prefill:b{b}{sfx}")
                    self.warmup_program_seconds.setdefault(
                        f"prefill:b{b}{sfx}",
                        round(time.perf_counter() - t0, 3),
                    )
                    n += 1
            if self.prefill_chunk:
                for key in sorted(
                    set(self._prefill_suffix_jit) - suffix_before
                ):
                    self.warmup_programs.append(
                        f"prefill:b{key[0]}:w{key[1]}{sfx}"
                    )
                    n += 1
            # every attention-window bucket compiles up front: a window
            # first hit mid-serving would stall that decode block for the
            # compile (seconds on a big model), wrecking its requests' p99.
            # Only the program the scheduler will actually run compiles —
            # step_k when decode_block > 1, the single-token step otherwise.
            for w in self._window_buckets():
                if self.decode_block > 1:
                    self.step_k(
                        np.zeros(self.n_slots, np.int32),
                        np.zeros(self.n_slots, bool),
                        np.zeros(self.n_slots, np.float32),
                        0,
                        np.full(self.n_slots, -1, np.int32),
                        np.zeros(self.n_slots, np.int32),
                        self.decode_block,
                        window=w,
                    )
                    self.warmup_programs.append(
                        f"decode_k:k{self.decode_block}:w{w}{sfx}"
                    )
                else:
                    self.step(
                        np.zeros(self.n_slots, np.int32),
                        np.zeros(self.n_slots, bool),
                        np.zeros(self.n_slots, np.float32),
                        0,
                        window=w,
                    )
                    self.warmup_programs.append(f"decode:w{w}{sfx}")
                n += 1
            # KV prefix reuse on: the suffix-prefill program for each
            # prefix window would otherwise first-compile on the first
            # shared-prefix request mid-serving (seconds on a big model).
            # Warm the canonical shape — smallest suffix bucket per window
            # (the "long system prompt + short novel question" pattern);
            # other suffix buckets compile organically.  Garbage K/V lands
            # in the reserved sink block 0, never read; the prefill
            # counters are restored so reuse accounting stays honest.
            if (
                self.prefix_index is not None
                and os.environ.get("SCT_WARMUP_SUFFIX", "1") != "0"
            ):
                bucket = self.prefill_buckets[0]
                pf, pfr = self.prefills, self.prefills_reused
                for pw in self._prefix_windows():
                    payload = {
                        "padded": np.zeros((1, bucket), np.int32),
                        "prefix_len": pw,
                        "length": pw,
                        "slot": 0,
                        "blocks": np.zeros(self.max_blocks_per_slot, np.int32),
                        "suffix_blocks": np.zeros(
                            bucket // self.kv_block_size, np.int32
                        ),
                        "window": pw,
                        "temperature": 0.0,
                        "seed": 0,
                    }
                    if self.spec_draft:
                        payload["hist_seed"] = np.zeros(
                            self.spec_hist, np.int32
                        )
                    if self.driver is not None:
                        self.driver.lead(self._mh_prefill_suffix_key, payload)
                    else:
                        self._exec_prefill_suffix(payload)
                    self.warmup_programs.append(
                        f"suffix:b{bucket}:w{pw}{sfx}"
                    )
                    n += 1
                self.prefills, self.prefills_reused = pf, pfr
            # pooled-embedding programs: one per prompt bucket, same set the
            # /embeddings route serves (pure forward — no slot, no reset
            # interaction; warmed last so generation readiness is unchanged
            # when the endpoint is off)
            if self.embed_enabled:
                for b in self.prefill_buckets:
                    t0 = time.perf_counter()
                    self.embed(np.ones(b, np.int32))
                    self.warmup_program_seconds[f"embed:b{b}{sfx}"] = (
                        time.perf_counter() - t0
                    )
                    self.warmup_programs.append(f"embed:b{b}{sfx}")
                    n += 1
            # warmup wrote garbage into slot 0 and advanced nothing real
            self.reset()
            self._in_warmup = False
            return n

    def _prefix_windows(self) -> list[int]:
        """Every window :meth:`_prefix_window` can return: block-size
        powers-of-two up to max_seq (bounded — 8 values at max_seq 2048
        with 16-token blocks)."""
        out = []
        w = self.kv_block_size
        while w < self.cfg.max_seq:
            out.append(w)
            w *= 2
        out.append(self.cfg.max_seq)
        return out

    def _window_buckets(self) -> list[int]:
        out = []
        w = 64
        while w < self.cfg.max_seq:
            out.append(w)
            w *= 2
        out.append(self.cfg.max_seq)
        return out

    def _exec_reset(self, payload: dict) -> None:
        with self._lock:
            zero = jax.device_put(
                np.zeros(self.n_slots, np.int32), self._cache["pos"].sharding
            )
            out = {**self._cache, "pos": zero}
            if "d_pos" in out:
                # the draft clock resets with the target's (rows above it
                # become unreachable, same as the main pool)
                out["d_pos"] = jax.device_put(
                    np.zeros(self.n_slots, np.int32),
                    self._cache["d_pos"].sharding,
                )
            self._cache = out

    def reset(self) -> None:
        """Zero every slot position and reclaim every block reservation
        (cache contents become unreachable)."""
        self._pos_ceiling[:] = 0
        for slot in list(self._slot_blocks):
            self.release_slot(slot)
        if self.prefix_index is not None:
            # drop everything release_slot absorbed (warmup admits garbage
            # prompts; a reset must leave the index empty) — zero-ref only,
            # and after the release loop every entry IS zero-ref
            self._free_blocks.extend(self.prefix_index.flush())
        if self.host_store is not None:
            # a reset empties every tier: demoted warmup chains must not
            # survive to be promoted into a clean pool
            self.host_store.flush()
        self._peer_chains.clear()
        self._slot_tier.clear()
        self._slot_promoted.clear()
        self._pending_draft_prefill.clear()
        if self.driver is not None:
            self.driver.lead(self._mh_reset_key, {})
            return
        self._exec_reset({})

    def prefix_snapshot(self) -> dict | None:
        """The KV prefix-reuse index state for ``GET /stats/cache``."""
        if self.prefix_index is None:
            return None
        snap = self.prefix_index.snapshot()
        snap["free_blocks"] = len(self._free_blocks)
        snap["pool_blocks"] = self.kv_blocks - 1
        snap["prefills"] = self.prefills
        snap["prefills_reused"] = self.prefills_reused
        snap["kv_imports"] = self.imports
        # compact routing digest: the gateway's prefix-aware router polls
        # this to steer shared-prefix requests at the warm replica
        snap["digest"] = self.prefix_index.digest()
        # per-tier telemetry (docs/CACHING.md "Tiered prefix store"): the
        # same six fields for every tier, zero-filled where a tier has no
        # such flow, so dashboards can stack them without schema checks
        idx = self.prefix_index.snapshot()
        tiers: dict[str, dict] = {
            "hbm": {
                "hits": idx["hits"],
                "misses": idx["misses"],
                "promotions": 0,
                "demotions": idx["evicted"],
                "bytes": len(self.prefix_index) * self.kv_bytes_per_block(),
                "pull_count": 0,
            },
            "peer": {
                "hits": self.peer_hits,
                "misses": 0,
                "promotions": self.peer_installs,
                "demotions": 0,
                "bytes": 0,
                "pull_count": self.peer_serves,
            },
        }
        if self.host_store is not None:
            st = self.host_store.snapshot()
            tiers["dram"] = {
                "hits": st["hits"],
                "misses": st["misses"],
                "promotions": st["promotions"],
                "demotions": st["demotions"],
                "bytes": st["bytes"],
                "pull_count": 0,
                "entries": st["entries"],
                "budget_bytes": st["budget_bytes"],
                "evictions": st["evictions"],
                "rejected": st["rejected"],
            }
            # the DRAM digest rides the same gossip as the HBM one: a
            # replica holding a chain in DRAM still serves it warm (one
            # promotion scatter), so the router should route/pull for it
            tiers["dram"]["digest"] = self.host_store.digest()
        snap["tiers"] = tiers
        m = DEFAULT_METRICS
        for tier, t in tiers.items():
            m.prefix_tier_hits.labels(self.name, tier).set(t["hits"])
            m.prefix_tier_promotions.labels(self.name, tier).set(
                t["promotions"]
            )
            m.prefix_tier_demotions.labels(self.name, tier).set(
                t["demotions"]
            )
            m.prefix_tier_bytes.labels(self.name, tier).set(t["bytes"])
        return snap


@dataclasses.dataclass(eq=False)  # identity eq: fields hold arrays/futures
class _Request:
    prompt: np.ndarray
    max_new_tokens: int
    temperature: float
    eos_id: int | None
    future: asyncio.Future
    out: list[int] = dataclasses.field(default_factory=list)
    # streaming hook: called with each sampled token as it lands (in
    # event-loop context, decode_block tokens at a time per device fetch)
    on_token: "Callable[[int], None] | None" = None
    # flight-recorder timestamps: submission, first sampled token, and the
    # last delivery (feeds the per-slot inter-token-latency ledger)
    t0: float = 0.0
    t_first_token: float = 0.0
    t_last_tok: float = 0.0
    # the submitting request's live span (captured at submit, same loop):
    # first-token lands on it as an event even though the scheduler loop
    # runs outside the request's contextvar scope
    span: Any = None
    # QoS: priority class + absolute monotonic deadline (None = no SLO),
    # captured from the request context at submit
    priority: str = qos.PRIO_INTERACTIVE
    deadline: float | None = None
    # disagg (docs/DISAGGREGATION.md): a prefill-only request resolves with
    # (slot, first_token) after its prefill and PINS the slot for a KV
    # export; an imported request skips prefill entirely — its KV blocks
    # and first token arrived from another engine's handoff
    prefill_only: bool = False
    imported: dict | None = None
    # batched multi-LoRA (docs/MULTITENANT.md): the named adapter this
    # request decodes through (None = base model / null adapter row)
    adapter: str | None = None
    # generation-forensics ledger entry (obs/timeline.py; None when the
    # ledger is off) and the terminal reason _token_done computed — every
    # event is stamped from host-held values only
    timeline: Any = None
    done_reason: str | None = None
    # per-request cost accumulators (obs/metering.py): device seconds
    # attributed by token share, prompt tokens actually prefilled, and
    # prefix-tier tokens saved — stamped onto the timeline terminal so a
    # single trace shows its own cost
    u_device_s: float = 0.0
    u_tokens_prefill: int = 0
    u_saved_tokens: int = 0
    u_saved_tier: str = ""
    u_terminal_metered: bool = False
    # embeddings (docs/GRAPHS.md): a pooled-embedding request rides the
    # same bounded intake + QoS pops but consumes no slot or KV — the run
    # loop batches the wave at a sync point and resolves with the vector
    embed_only: bool = False
    # cascade confidence (docs/GRAPHS.md): sum/count of per-token top-2
    # logit margins delivered to this request, accumulated by _deliver
    # from the stash the fused-block fetch fills — zero extra syncs
    conf_sum: float = 0.0
    conf_n: int = 0


class GenerationScheduler:
    """Continuous-batching front: admits requests into free slots while
    decode steps keep running for in-flight ones.

    QoS: the intake is BOUNDED (``maxsize``, env ``SCT_GEN_QUEUE_MAX``) —
    overflow raises a typed :class:`~seldon_core_tpu.qos.QueueFull` the
    engine maps to 429; batch-priority work may only fill half the bound so
    it can never starve interactive admission.  Queue pops are
    priority-ordered, expired requests are failed with a 504 *before* a
    prefill or decode step is spent on them, and a client that disconnects
    before its slot is assigned is withdrawn from the queue entirely."""

    def __init__(
        self,
        model: GenerativeModel,
        *,
        maxsize: int | None = None,
        overlap: bool | None = None,
    ):
        self.model = model
        # overlapped pipeline (docs/PERFORMANCE.md): dispatch block N+1
        # from the device carry before consuming block N's tokens.  On by
        # default for fused blocks; SCT_GEN_OVERLAP=0 (or the ``overlap``
        # graph parameter) restores the strictly sequential loop.
        if overlap is None:
            overlap = os.environ.get("SCT_GEN_OVERLAP", "1") != "0"
        self.overlap = bool(overlap) and model.decode_block > 1
        # waiting requests (priority-sorted at pop time) + a wake event the
        # run loop parks on when fully idle
        self._waiting: list[_Request] = []
        self._wake = asyncio.Event()
        self._maxsize = (
            int(maxsize)
            if maxsize is not None
            else int(os.environ.get("SCT_GEN_QUEUE_MAX", "256"))
        )
        self._batch_cap = max(1, self._maxsize // 2) if self._maxsize else 0
        # requests admitted to a slot but not to the KV pool (OutOfKVBlocks):
        # retried ahead of the queue as completions free blocks
        self._overflow: list[_Request] = []
        # disagg: slots pinned by a prefill-only admission (KV export in
        # progress) — excluded from admission until released, and released
        # only at a sync point so block reuse never races a dispatched
        # decode block
        self._external: set[int] = set()
        self._external_release: list[int] = []
        # chunked prefill (docs/PERFORMANCE.md §7): admissions whose prompt
        # is mid-prefill — one chunk advances per decode sync point so a
        # long admission never stalls in-flight streams for more than one
        # chunk's latency.  Their slots are reserved but not decode-active.
        self._prefilling: list[dict] = []
        self._prefill_slots: set[int] = set()
        # peer-pulled prefix chains waiting to install (docs/CACHING.md
        # "Tiered prefix store"): the scatter grabs pool blocks, so it
        # only runs at a sync point, like external releases
        self._prefix_installs: list[tuple] = []
        self._task: asyncio.Task | None = None
        self._closed = False
        # chip packing (docs/PACKING.md): when attached to a DeviceArbiter
        # the run loop brackets every fused block with the device grant,
        # and the arbiter may preempt this deployment — active slots
        # export into the host-DRAM suspend store (whole-slot handoff
        # frames) and resume bit-exactly at a later sync point
        self._arbiter = None
        self._arb_key: str | None = None
        # batch-class registrant for the co-resident draft model's prompt
        # prefills (spec_method='draft'; attach_arbiter sets it)
        self._arb_draft_key: str | None = None
        self._preempt = False
        self._suspended: list[dict] = []
        self._suspend_store = None
        self._suspend_seq = 0
        # queue-wait EWMA (host bookkeeping only): the deadline-pressure
        # signal the arbiter reads; time-decayed so a drained burst stops
        # preempting co-tenants
        self._qwait_ewma: float | None = None
        self._qwait_stamp = 0.0
        self.suspends = 0
        self.resumes = 0
        self.suspend_rejected = 0
        # live migration (docs/RESILIENCE.md): drain_begin pauses
        # admission and parks every active slot; the engine's /admin/drain
        # endpoint then ships the frames to a peer (or drain_finish
        # resumes them locally).  _quiesced fires in the run loop once no
        # slot is device-resident.
        self._draining = False
        self._quiesced = asyncio.Event()
        self.drains = 0
        self.drained_out = 0
        # Random base so temperature>0 sampling differs across restarts and
        # replicas; within one process the sequence stays deterministic.
        self._seed = int.from_bytes(os.urandom(4), "little")

    def _next_seed(self) -> int:
        self._seed = (self._seed + 1) % (2**31 - 1)
        return self._seed

    # ------------------------------------------- lifecycle timeline feeds
    # (obs/timeline.py; docs/OBSERVABILITY.md "generation forensics").
    # Every event is stamped from values the host ALREADY holds — fetched
    # token counts, reservation bookkeeping, queue state — never a device
    # array: the <=1-sync-per-fused-block audit runs with the ledger on.

    def _begin_tl(self, req: _Request, kind: str = "generate") -> None:
        req.timeline = TIMELINE.begin(
            current_trace_id(),
            model=self.model.name,
            kind=kind,
            prompt_tokens=int(req.prompt.size),
            max_new_tokens=int(req.max_new_tokens),
            priority=req.priority,
        )

    def _tl(self, req: _Request, name: str, span: bool = True, **attrs) -> None:
        """One lifecycle event: the timeline entry plus (bounded) the same
        event folded onto the request's generation span."""
        if req.timeline is not None:
            req.timeline.event(name, **attrs)
        if span and req.span is not None and len(req.span.span.events) < 256:
            req.span.event(name, **attrs)

    def _usage_attrs(self, req: _Request) -> dict:
        """The request's final cost totals, stamped onto its terminal
        event so one trace shows what it spent (host-held values only)."""
        out = {
            "device_ms": round(req.u_device_s * 1e3, 3),
            "tokens_in": int(req.prompt.size),
            "tokens_out": len(req.out),
        }
        if req.u_saved_tokens:
            out["tokens_saved"] = int(req.u_saved_tokens)
            out["saved_tier"] = req.u_saved_tier
        return out

    def _meter_terminal(self, req: _Request, reason: str) -> None:
        """Fold the request's outcome into the usage meter exactly once
        (first terminal wins, matching the timeline)."""
        if reason in ("eos", "budget", "exported"):
            METER.add(
                self.model.name, req.adapter or "", req.priority,
                requests_completed=1,
            )
        elif reason == "shed":
            METER.add(
                self.model.name, req.adapter or "", req.priority,
                requests_shed=1,
            )
        else:  # deadline-reap / disconnect / error: spent, not delivered
            METER.add(
                self.model.name, req.adapter or "", req.priority,
                requests_reaped=1, tokens_wasted=len(req.out),
            )

    def _meter_admit(self, req: _Request, snap: dict | None) -> None:
        """Fold one admission's prefill cost into the usage meter: prompt
        tokens actually prefilled on device, and prefix-tier tokens SAVED
        (hbm/dram/peer — reuse of KV someone already paid for), both from
        the host-side reservation bookkeeping the admit event reads."""
        snap = snap or {}
        prompt_n = int(req.prompt.size)
        saved = min(int(snap.get("prefix_tokens") or 0), prompt_n)
        tier = str(snap.get("tier") or "none")
        fields: dict = {"tokens_prefill": max(0, prompt_n - saved)}
        if saved and tier in ("hbm", "dram", "peer"):
            fields[f"tokens_saved_{tier}"] = saved
            req.u_saved_tokens += saved
            req.u_saved_tier = tier
        req.u_tokens_prefill = fields["tokens_prefill"]
        METER.add(
            self.model.name, req.adapter or "", req.priority, **fields
        )

    def _end_tl(self, req: _Request, reason: str, **attrs) -> None:
        if req.done_reason is None:
            req.done_reason = reason
        if not req.u_terminal_metered:
            # exactly-once outcome metering: done_reason may have been
            # stamped by the device-visible transition (_token_done)
            # before this terminal event runs
            req.u_terminal_metered = True
            self._meter_terminal(req, req.done_reason)
        attrs["usage"] = self._usage_attrs(req)
        if req.timeline is not None:
            req.timeline.end(reason, **attrs)
        if req.span is not None and len(req.span.span.events) < 256:
            req.span.event("terminal", reason=reason, **attrs)

    def _note_shed(
        self, priority: str, depth: int, cap: int, adapter: str | None = None
    ) -> None:
        """A QueueFull shed leaves a terminal-only timeline entry so the
        trace's forensics say WHY the request never ran — and a shed-cost
        row in the usage meter (zero device time, by construction)."""
        METER.add(
            self.model.name, adapter or "", priority, requests_shed=1
        )
        tl = TIMELINE.begin(
            current_trace_id(), model=self.model.name, priority=priority
        )
        if tl is not None:
            tl.end(
                "shed", depth=depth, cap=cap,
                usage={"device_ms": 0.0, "tokens_in": 0, "tokens_out": 0},
            )

    async def submit(
        self,
        prompt: np.ndarray,
        *,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        eos_id: int | None = None,
        on_token: "Callable[[int], None] | None" = None,
        adapter: str | None = None,
        info: dict | None = None,
    ) -> np.ndarray:
        """Generate up to ``max_new_tokens`` ids for a 1-D prompt.

        ``on_token`` (optional) fires per sampled token in event-loop
        context — the streaming hook; tokens arrive ``decode_block`` at a
        time per device fetch.  ``adapter`` names a resident LoRA adapter
        to decode through (docs/MULTITENANT.md).  ``info`` (optional) is an
        out-param dict stamped with per-request extras on completion —
        today the cascade confidence signal (docs/GRAPHS.md): mean top-2
        logit margin over delivered tokens, when ``conf_signal`` is on."""
        if self._closed:
            raise RuntimeError("GenerationScheduler is closed")
        prompt = np.asarray(prompt, np.int32).ravel()
        if prompt.size < 1:
            raise GraphUnitError("empty prompt")
        vocab = self.model.cfg.vocab_size
        if prompt.min() < 0 or prompt.max() >= vocab:
            # JAX gather would silently clamp out-of-range ids into arbitrary
            # embedding rows — garbage generations with status 200
            raise GraphUnitError(
                f"token ids must be in [0, {vocab}); got "
                f"[{int(prompt.min())}, {int(prompt.max())}]"
            )
        if prompt.size >= self.model.cfg.max_seq:
            raise GraphUnitError(
                f"prompt length {prompt.size} must be < max_seq "
                f"{self.model.cfg.max_seq}"
            )
        if max_new_tokens < 1:
            return np.zeros(0, np.int32)
        # the cache cannot grow past max_seq
        max_new_tokens = min(
            int(max_new_tokens), self.model.cfg.max_seq - int(prompt.size)
        )
        # brownout: under sustained overload the active admission
        # controller clamps answer length before availability degrades
        max_new_tokens = qos.clamp_max_new_tokens(max_new_tokens)
        priority = qos.get_priority()
        depth = len(self._waiting) + len(self._overflow)
        cap = (
            self._maxsize
            if priority == qos.PRIO_INTERACTIVE
            else self._batch_cap
        )
        if self._maxsize and depth >= cap:
            self._note_shed(priority, depth, cap, adapter)
            raise qos.QueueFull(
                f"generation queue is full ({depth} waiting, cap {cap} "
                f"for {priority})"
            )
        self._ensure_run_task()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        from seldon_core_tpu.obs import current_span

        req = _Request(
            prompt, max_new_tokens, float(temperature), eos_id, fut,
            on_token=on_token, t0=time.perf_counter(),
            span=current_span(),
            priority=priority, deadline=qos.get_deadline(),
            adapter=adapter or None,
        )
        self._begin_tl(req)
        self._tl(req, "queued", span=False, depth=len(self._waiting))
        self._waiting.append(req)
        self._wake.set()
        try:
            out = await fut
            if info is not None and req.conf_n:
                info["confidence"] = req.conf_sum / req.conf_n
                info["conf_tokens"] = req.conf_n
            return out
        except asyncio.CancelledError:
            # cancel-on-disconnect: the client is gone — withdraw before a
            # slot/prefill is spent (in-slot requests are reaped by the run
            # loop's sweep via the now-cancelled future)
            if req in self._waiting:
                self._waiting.remove(req)
            if req in self._overflow:
                self._overflow.remove(req)
            self._end_tl(req, "disconnect", stage="queue")
            raise

    # ------------------------------------------------------ disagg entries

    def _validate_prompt(self, prompt: np.ndarray) -> np.ndarray:
        prompt = np.asarray(prompt, np.int32).ravel()
        if prompt.size < 1:
            raise GraphUnitError("empty prompt")
        vocab = self.model.cfg.vocab_size
        if prompt.min() < 0 or prompt.max() >= vocab:
            raise GraphUnitError(
                f"token ids must be in [0, {vocab}); got "
                f"[{int(prompt.min())}, {int(prompt.max())}]"
            )
        if prompt.size >= self.model.cfg.max_seq:
            raise GraphUnitError(
                f"prompt length {prompt.size} must be < max_seq "
                f"{self.model.cfg.max_seq}"
            )
        return prompt

    def _enqueue(self, req: _Request) -> None:
        depth = len(self._waiting) + len(self._overflow)
        cap = (
            self._maxsize
            if req.priority == qos.PRIO_INTERACTIVE
            else self._batch_cap
        )
        if self._maxsize and depth >= cap:
            self._note_shed(req.priority, depth, cap, req.adapter)
            raise qos.QueueFull(
                f"generation queue is full ({depth} waiting, cap {cap} "
                f"for {req.priority})"
            )
        self._ensure_run_task()
        self._tl(req, "queued", span=False, depth=len(self._waiting))
        self._waiting.append(req)
        self._wake.set()

    async def _await_withdrawing(self, req: _Request):
        try:
            return await req.future
        except asyncio.CancelledError:
            if req in self._waiting:
                self._waiting.remove(req)
            if req in self._overflow:
                self._overflow.remove(req)
            self._end_tl(req, "disconnect", stage="queue")
            raise

    async def submit_prefill(
        self, prompt: np.ndarray, *, temperature: float = 0.0,
        adapter: str | None = None,
    ) -> tuple[int, int]:
        """Disagg prefill-only admission (docs/DISAGGREGATION.md): prefill
        ``prompt`` into a free slot and return ``(slot, first_token)``
        WITHOUT decoding.  The slot is PINNED — excluded from later
        admissions, its blocks unreclaimable — until
        :meth:`release_external` returns it, so a KV export can read the
        blocks at leisure and a failed handoff leaks nothing."""
        if self._closed:
            raise RuntimeError("GenerationScheduler is closed")
        prompt = self._validate_prompt(prompt)
        from seldon_core_tpu.obs import current_span

        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        req = _Request(
            prompt, 1, float(temperature), None, fut,
            t0=time.perf_counter(), span=current_span(),
            priority=qos.get_priority(), deadline=qos.get_deadline(),
            adapter=adapter or None,
        )
        req.prefill_only = True
        self._begin_tl(req, kind="prefill")
        self._enqueue(req)
        return await self._await_withdrawing(req)

    async def submit_embed(self, prompt: np.ndarray) -> np.ndarray:
        """Pooled-embedding admission (docs/GRAPHS.md): ride the same
        bounded intake, QoS priority pops, and deadline reaping as
        generation, but consume no slot or KV — the run loop batches the
        waiting embed wave at its next sync point and resolves each with
        its (E,) float32 vector."""
        if self._closed:
            raise RuntimeError("GenerationScheduler is closed")
        prompt = self._validate_prompt(prompt)
        from seldon_core_tpu.obs import current_span

        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        req = _Request(
            prompt, 1, 0.0, None, fut,
            t0=time.perf_counter(), span=current_span(),
            priority=qos.get_priority(), deadline=qos.get_deadline(),
        )
        req.embed_only = True
        self._begin_tl(req, kind="embed")
        self._enqueue(req)
        return await self._await_withdrawing(req)

    async def _admit_embeds(self, reqs: list["_Request"]) -> None:
        """Serve one wave of embed-only requests: dispatch every forward
        first (async), then ONE device_get for the whole wave — N prompts
        cost one host sync, mirroring the fused-block discipline."""

        def dispatch_and_fetch():
            placed: list[tuple[_Request, Any]] = []
            errors: list[tuple[_Request, Exception]] = []
            for req in reqs:
                try:
                    placed.append((req, self.model.embed_dispatch(req.prompt)))
                except Exception as e:  # per-request: one bad prompt
                    errors.append((req, e))  # must not fail the wave
            # sct: host-sync-ok embed wave sync point
            vecs = jax.device_get([v for _, v in placed]) if placed else []
            return placed, errors, vecs

        t0 = time.perf_counter()
        placed, errors, vecs = await asyncio.to_thread(dispatch_and_fetch)
        batch_s = time.perf_counter() - t0
        total_toks = sum(int(r.prompt.size) for r, _ in placed) or 1
        for (req, _), vec in zip(placed, vecs):
            share_s = batch_s * int(req.prompt.size) / total_toks
            req.u_device_s += share_s
            METER.add(
                self.model.name, req.adapter or "", req.priority,
                device_s=share_s, tokens_prefill=int(req.prompt.size),
            )
            self._note_queue_wait(req)
            self._tl(req, "embed", tokens=int(req.prompt.size))
            arr = np.asarray(vec, np.float32)
            if not req.future.done():
                req.future.set_result(arr)
            self._end_tl(req, "embedded", dim=int(arr.shape[-1]))
        for req, e in errors:
            if not req.future.done():
                req.future.set_exception(e)
            self._end_tl(req, "error", stage="embed")

    async def submit_imported(
        self,
        prompt: np.ndarray,
        *,
        first_token: int,
        k: np.ndarray,
        v: np.ndarray,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        eos_id: int | None = None,
        on_token: "Callable[[int], None] | None" = None,
        k_scale: np.ndarray | None = None,
        v_scale: np.ndarray | None = None,
        adapter: str | None = None,
        spec_state: dict | None = None,
    ) -> np.ndarray:
        """Disagg decode-side admission: continue a generation whose
        prompt KV (``k``/``v``) and first sampled token arrived from a
        prefill engine's handoff.  The blocks import into this pool at the
        scheduler's next sync point; the result (first token included) is
        exactly what a unified engine returns for the same request."""
        if self._closed:
            raise RuntimeError("GenerationScheduler is closed")
        prompt = self._validate_prompt(prompt)
        max_new_tokens = min(
            max(1, int(max_new_tokens)),
            self.model.cfg.max_seq - int(prompt.size),
        )
        max_new_tokens = qos.clamp_max_new_tokens(max_new_tokens)
        from seldon_core_tpu.obs import current_span

        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        req = _Request(
            prompt, max_new_tokens, float(temperature), eos_id, fut,
            on_token=on_token, t0=time.perf_counter(), span=current_span(),
            priority=qos.get_priority(), deadline=qos.get_deadline(),
            adapter=adapter or None,
        )
        req.imported = {
            "first_token": int(first_token), "k": k, "v": v,
            "k_scale": k_scale, "v_scale": v_scale,
            "spec": spec_state,
        }
        self._begin_tl(req, kind="imported")
        self._enqueue(req)
        return await self._await_withdrawing(req)

    def release_external(self, slot: int) -> None:
        """Return a :meth:`submit_prefill`-pinned slot to the pool.  The
        actual release happens at the run loop's next sync point — block
        reuse must never race a dispatched decode block — and is idempotent
        there."""
        self._external_release.append(int(slot))
        self._wake.set()

    def _drain_external_releases(self) -> None:
        while self._external_release:
            slot = self._external_release.pop()
            self._external.discard(slot)
            self.model.release_slot(slot)

    async def install_prefix(
        self,
        tokens: np.ndarray,
        k: np.ndarray,
        v: np.ndarray,
        *,
        k_scale: np.ndarray | None = None,
        v_scale: np.ndarray | None = None,
        adapter: str | None = None,
    ) -> int:
        """Install a peer-pulled prefix chain into the pool + index at
        the run loop's next sync point (the scatter takes free blocks, so
        it must never race a dispatched decode block).  Resolves to the
        number of chain levels installed (0 when everything was already
        resident or the pool is too hot to cache the pull)."""
        if self._closed:
            raise RuntimeError("GenerationScheduler is closed")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._prefix_installs.append(
            (
                {
                    "tokens": tokens, "k": k, "v": v,
                    "k_scale": k_scale, "v_scale": v_scale,
                    "adapter": adapter,
                },
                fut,
            )
        )
        self._ensure_run_task()
        self._wake.set()
        return await fut

    async def _drain_prefix_installs(self) -> None:
        while self._prefix_installs:
            payload, fut = self._prefix_installs.pop(0)
            try:
                n = await asyncio.to_thread(
                    self.model.install_prefix_chain,
                    payload["tokens"], payload["k"], payload["v"],
                    payload["k_scale"], payload["v_scale"],
                    payload["adapter"],
                )
            except Exception as e:
                if not fut.done():
                    fut.set_exception(e)
                continue
            if not fut.done():
                fut.set_result(n)

    # ------------------------------------------- chip packing (arbitration)
    # docs/PACKING.md: the scheduler side of SLO-arbitrated time-sharing —
    # the device grant brackets every fused block, and preemption/resume
    # are verbs the arbiter invokes between blocks, never inside one.

    def attach_arbiter(
        self,
        arbiter,
        *,
        priority: str = qos.PRIO_INTERACTIVE,
        slo_ms: float | None = None,
    ) -> None:
        """Join a packed chip: register with ``arbiter`` under this
        model's name (the arbiter de-duplicates colliding names) and
        start bracketing fused blocks with its grant.  With a co-resident
        draft model (``spec_method='draft'``) a SECOND, batch-class
        registrant covers its prompt prefills: they stop running inline
        at admission and drain at sync points under the draft grant, so
        interactive verify blocks never queue behind draft warm-up work
        (docs/PACKING.md + PERFORMANCE.md §6)."""
        self._arbiter = arbiter
        self._arb_key = arbiter.register(
            self.model.name, scheduler=self, priority=priority, slo_ms=slo_ms
        )
        if getattr(self.model, "spec_method", None) == "draft":
            self._arb_draft_key = arbiter.register(
                f"{self.model.name}/draft", scheduler=self,
                priority=qos.PRIO_BATCH,
            )
            self.model.defer_draft_prefill = True

    def detach_arbiter(self) -> None:
        if self._arbiter is not None:
            if self._arb_draft_key is not None:
                self._arbiter.unregister(self._arb_draft_key)
                self._arb_draft_key = None
                self.model.defer_draft_prefill = False
            self._arbiter.unregister(self._arb_key)
            self._arbiter = None
            self._arb_key = None

    async def _drain_draft_prefills(self) -> None:
        """Run deferred draft-model prefills under the batch-class draft
        grant (sync points only — never between a dispatch and its
        fetch, so the one-sync-per-block audit holds)."""
        if not getattr(self.model, "_pending_draft_prefill", None):
            return
        # local refs: close() detaches the arbiter concurrently with the
        # run loop, and the release must pair with the acquire we made
        arb, key = self._arbiter, self._arb_draft_key
        if arb is not None and key is not None:
            await arb.acquire(key)
            try:
                await asyncio.to_thread(self.model.drain_draft_prefills)
            finally:
                arb.release(key)
            return
        await asyncio.to_thread(self.model.drain_draft_prefills)

    async def _arb_acquire(self) -> None:
        if self._arbiter is not None:
            # _arb_release() pairs it on every park and error path
            # sct: pairing-ok ownership transfer to _arb_release()
            await self._arbiter.acquire(self._arb_key)

    def _arb_release(self) -> None:
        # idempotent: every park and error path releases defensively — a
        # parked co-tenant must never wait on a scheduler that is itself
        # waiting
        if self._arbiter is not None:
            self._arbiter.release(self._arb_key)

    def _arb_contended(self) -> bool:
        return self._arbiter is not None and self._arbiter.contended(
            self._arb_key
        )

    def queue_pressure(self) -> float:
        """Deadline pressure in seconds: max of the (time-decayed)
        queue-wait EWMA and the oldest live waiter's age.  Host
        bookkeeping only — the arbiter polls this at grant edges."""
        now = time.perf_counter()
        oldest = max(
            (now - r.t0 for r in self._waiting if not r.future.done()),
            default=0.0,
        )
        ewma = 0.0
        if self._qwait_ewma is not None:
            # 1 s half-life: a drained burst's pressure fades instead of
            # preempting co-tenants forever
            ewma = self._qwait_ewma * (0.5 ** max(0.0, now - self._qwait_stamp))
        return max(ewma, oldest)

    def _note_queue_wait(self, req: _Request) -> None:
        """Fold one admission's queue wait into the EWMA.  Resumed
        suspend records skip it: their t0 is the ORIGINAL submission, so
        counting them would report the suspension as queue pressure."""
        if req.imported is not None and req.imported.get("resumed"):
            return
        wait = max(0.0, time.perf_counter() - req.t0)
        e = self._qwait_ewma
        self._qwait_ewma = wait if e is None else (0.8 * e + 0.2 * wait)
        self._qwait_stamp = time.perf_counter()

    def request_preempt(self) -> None:
        """Arbiter verb: suspend this deployment's active slots at the
        next sync point and hold admissions until resumed."""
        self._preempt = True
        self._wake.set()

    def request_resume(self) -> None:
        """Arbiter verb: lift the preemption — suspended records re-queue
        at the next sync point and resume bit-exactly."""
        self._preempt = False
        self._wake.set()

    def _suspend_budget_bytes(self) -> int:
        return int(
            float(os.environ.get("SCT_PACK_SUSPEND_GB", "1") or 1) * (1 << 30)
        )

    def _get_suspend_store(self):
        if self._suspend_store is None:
            from seldon_core_tpu.cache.tiers import SuspendStore

            # getattr: duck-typed stand-in models (tests) predate the
            # host-DRAM ledger
            self._suspend_store = SuspendStore(
                self._suspend_budget_bytes(),
                on_bytes=getattr(self.model, "note_suspend_bytes", None),
            )
        return self._suspend_store

    async def _suspend_active(self, slots, cur, temps, active) -> int:
        """The preemption verb's device half, at a sync point only: for
        every active slot, export its KV (prompt + emitted tokens so far)
        as ONE disagg handoff frame — int8 blocks + scales verbatim —
        park it in the suspend store, and free the slot's blocks.  The
        request object stays alive (future, streaming hook, span,
        timeline); only its device residency is taken.  A record the
        store cannot hold leaves its slot RUNNING — best-effort
        preemption never kills a generation.  Returns slots suspended."""
        from seldon_core_tpu.disagg.handoff import encode_handoff

        store = self._get_suspend_store()
        n_susp = 0
        for i in range(len(slots)):
            req = slots[i]
            if req is None or not active[i] or not req.out:
                continue
            self._tl(req, EVENT_PREEMPT, victim=self.model.name)
            n = len(req.out)
            # KV covers prompt + out[:-1] (the carry token's KV is not
            # written yet); out[-1] rides as the frame's first_token, so
            # the resume reserves (L+n-1) + (max_new-n+1) = L + max_new —
            # exactly the uninterrupted reservation
            hist = np.concatenate(
                [req.prompt, np.asarray(req.out[:-1], np.int32)]
            )

            def export(slot=i, hist=hist, req=req, carry=int(req.out[-1]), n=n):
                kv = self.model.export_slot_kv(slot, int(hist.size))
                k, v = kv[0], kv[1]
                ks, vs = (kv[2], kv[3]) if len(kv) == 4 else (None, None)
                spec = getattr(
                    self.model, "export_spec_state", lambda s: None
                )(slot)
                return encode_handoff(
                    hist, carry, k, v,
                    block_size=self.model.kv_block_size,
                    max_new_tokens=req.max_new_tokens - n + 1,
                    temperature=req.temperature,
                    eos_id=req.eos_id,
                    k_scale=ks, v_scale=vs,
                    priority=req.priority,
                    adapter=req.adapter,
                    spec_state=spec,
                )

            try:
                frame = await asyncio.to_thread(export)
            except Exception:
                log.exception(
                    "suspend export failed for slot %d; leaving it resident", i
                )
                continue
            self._suspend_seq += 1
            key = (id(req), self._suspend_seq)
            if not store.put(key, frame):
                # over the suspend budget: this slot keeps running
                self.suspend_rejected += 1
                self._tl(req, "suspend-rejected", bytes=len(frame))
                continue
            # free_block_count is a property; stand-in models may lack it
            before = int(getattr(self.model, "free_block_count", 0) or 0)
            self.model.release_slot(i)
            freed = int(getattr(self.model, "free_block_count", 0) or 0) - before
            self._suspended.append({
                "req": req, "key": key, "bytes": len(frame),
                "t_park": time.perf_counter(),
            })
            slots[i] = None
            active[i] = False
            self.suspends += 1
            n_susp += 1
            self._tl(
                req, EVENT_SUSPEND,
                victim=self.model.name, tokens=n,
                blocks_freed=int(freed), bytes=len(frame),
            )
        return n_susp

    def _meter_unpark(self, rec: dict) -> None:
        """Charge a suspend record's byte-seconds the moment it leaves the
        store (resume, reap, drain, or close) — bytes held x wall seconds
        parked, host bookkeeping only."""
        t0 = rec.get("t_park")
        if not t0:
            return
        req = rec["req"]
        METER.add(
            self.model.name, req.adapter or "", req.priority,
            suspend_byte_s=rec["bytes"] * (time.perf_counter() - t0),
        )

    def _drain_resumes(self) -> None:
        """Resume verb, at a sync point with preemption lifted: decode
        each suspend record back into an imported admission — the donated
        fused-scatter path — and re-queue the ORIGINAL request (its t0
        sorts it ahead of younger work in its class)."""
        from seldon_core_tpu.disagg.handoff import decode_handoff

        while self._suspended:
            rec = self._suspended.pop(0)
            self._meter_unpark(rec)
            req = rec["req"]
            frame = (
                self._suspend_store.take(rec["key"])
                if self._suspend_store is not None
                else None
            )
            if req.future.done():
                self._end_tl(req, "disconnect", stage="suspended")
                continue
            if frame is None:
                req.future.set_exception(
                    GraphUnitError("suspend record lost from the store")
                )
                self._end_tl(req, "error", stage="suspended")
                continue
            payload = decode_handoff(frame)
            req.imported = {
                "first_token": int(payload["first_token"]),
                "k": payload["k"],
                "v": payload["v"],
                "k_scale": payload.get("k_scale"),
                "v_scale": payload.get("v_scale"),
                "prompt": np.asarray(payload["prompt"], np.int32),
                "reserve_tokens": int(payload["max_new_tokens"]),
                "resumed": True,
                "spec": payload.get("spec_state"),
            }
            self.resumes += 1
            self._tl(req, "resume-queued", span=False)
            self._waiting.append(req)

    def _reap_suspended(self) -> None:
        """QoS sweep over parked suspend records: a cancelled or expired
        request must not hold suspend-store bytes until resume."""
        if not self._suspended:
            return
        now = time.monotonic()
        keep = []
        for rec in self._suspended:
            req = rec["req"]
            if req.future.done():
                if self._suspend_store is not None:
                    self._suspend_store.take(rec["key"])
                self._meter_unpark(rec)
                self._end_tl(req, "disconnect", stage="suspended")
                continue
            if req.deadline is not None and now >= req.deadline:
                if self._suspend_store is not None:
                    self._suspend_store.take(rec["key"])
                self._meter_unpark(rec)
                req.future.set_exception(qos.DeadlineExceeded(
                    f"deadline expired while suspended after "
                    f"{len(req.out)} tokens"
                ))
                DEFAULT_METRICS.qos_deadline_miss.labels(
                    self.model.name, "suspended"
                ).inc()
                qos.note_deadline_miss("suspended", req.priority)
                self._end_tl(
                    req, "deadline-reap", stage="suspended",
                    tokens=len(req.out),
                )
                continue
            keep.append(rec)
        self._suspended[:] = keep

    # -- live migration (docs/RESILIENCE.md "drain runbook") ---------------

    def drain_begin(self) -> None:
        """Admin verb, the device half of live migration: pause admission
        and suspend every active slot at the next sync point (the same
        bit-exact export preemption uses).  Pair with :meth:`drain_finish`
        once the frames have moved to a peer — or immediately, to resume
        everything locally when there is no peer."""
        self._draining = True
        # clear, never replace: drain_wait_quiesced may already hold this
        # event, and a waiter on a replaced one would hang forever
        self._quiesced.clear()
        self.drains += 1
        self._preempt = True
        self._wake.set()
        if self._task is None or self._task.done():
            # idle scheduler: the run loop only exists while work is in
            # flight, so nothing is device-resident and no loop turn will
            # ever fire the event — quiesce immediately instead of making
            # an idle victim's drain (the autoscaler's common shrink case)
            # sit out the full timeout
            self._quiesced.set()

    async def drain_wait_quiesced(self, timeout_s: float = 30.0) -> bool:
        """Block until no slot is device-resident (suspend records are
        parked; slots the store refused ran to completion)."""
        try:
            await asyncio.wait_for(self._quiesced.wait(), timeout_s)
            return True
        except asyncio.TimeoutError:
            return False

    def drain_take(self) -> list[tuple["_Request", bytes]]:
        """Pop every parked suspend record as ``(request, frame)`` — the
        migration payload, bit-exact v4 handoff frames.  Ownership of each
        request's completion moves to the caller (the drain endpoint
        relays the peer's continuation through
        :meth:`complete_migrated`)."""
        out: list[tuple[_Request, bytes]] = []
        while self._suspended:
            rec = self._suspended.pop(0)
            self._meter_unpark(rec)
            req = rec["req"]
            frame = (
                self._suspend_store.take(rec["key"])
                if self._suspend_store is not None
                else None
            )
            if req.future.done():
                self._end_tl(req, "disconnect", stage="suspended")
                continue
            if frame is None:
                req.future.set_exception(
                    GraphUnitError("suspend record lost from the store")
                )
                self._end_tl(req, "error", stage="suspended")
                continue
            self.drained_out += 1
            self._tl(req, "drain-export", bytes=len(frame))
            out.append((req, frame))
        return out

    def drain_abort(self, pairs: list[tuple["_Request", bytes]]) -> None:
        """The peer refused or died mid-migration: re-park the frames so
        :meth:`drain_finish` resumes them locally — a failed migration
        must never kill a generation."""
        store = self._get_suspend_store()
        for req, frame in pairs:
            if req.future.done():
                continue
            self._suspend_seq += 1
            key = (id(req), self._suspend_seq)
            if store.put(key, frame):
                self._suspended.append({
                    "req": req, "key": key, "bytes": len(frame),
                    "t_park": time.perf_counter(),
                })
                self._tl(req, "drain-abort", span=False)
            else:
                req.future.set_exception(
                    GraphUnitError("drain abort: suspend store full")
                )
                self._end_tl(req, "error", stage="suspended")

    def complete_migrated(self, req: "_Request", tokens) -> None:
        """Finish a migrated request with the peer's continuation.
        ``tokens[0]`` is the carry token (already delivered here before
        the drain); the rest stream through the request's hook and the
        future resolves with the full output — the client sees ONE
        uninterrupted stream."""
        for t in tokens[1:]:
            if self._token_done(req, int(t)):
                break
        req.done_reason = req.done_reason or "budget"
        self._complete(req)
        self._finish_tl(req)

    def drain_finish(self) -> None:
        """Lift the drain: admission resumes, and any records still
        parked (the no-peer path, or after :meth:`drain_abort`) re-queue
        and resume locally bit-exactly."""
        self._draining = False
        self._preempt = False
        self._wake.set()

    def adopt_seed(self, seed: int) -> None:
        """Drain cutover, REPLACEMENT-replica side: adopt the source's
        sampling-seed counter so migrated sampled streams continue with
        the exact keys the uninterrupted run would have used (greedy
        streams don't care).  Meant for a fresh engine taking over; any
        counter value is *valid* — this only pins determinism."""
        self._seed = int(seed) % (2**31 - 1)

    def packing_snapshot(self) -> dict:
        """Per-deployment packing ledger (``GET /stats/breakdown``)."""
        return {
            "arbitrated": self._arbiter is not None,
            "preempted": self._preempt,
            "draining": self._draining,
            "drains": self.drains,
            "drained_out": self.drained_out,
            "suspended": len(self._suspended),
            "suspends": self.suspends,
            "resumes": self.resumes,
            "suspend_rejected": self.suspend_rejected,
            "queue_pressure_ms": round(self.queue_pressure() * 1e3, 3),
            "suspend_store": (
                self._suspend_store.snapshot()
                if self._suspend_store is not None
                else None
            ),
        }

    def _ensure_run_task(self) -> None:
        """(Re)spawn the run-loop task on the CURRENT event loop.

        A fresh task gets a fresh wake event: asyncio primitives bind to
        the loop that first awaits them, and a scheduler driven through
        several short-lived loops (``asyncio.run`` per call — component
        tests, CLI tools) would otherwise park the new task on an event
        bound to a dead loop and crash it with a cross-loop RuntimeError
        that ``close()`` later re-raises."""
        if self._task is None or self._task.done():
            self._wake = asyncio.Event()
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def close(self) -> None:
        self._closed = True
        self.detach_arbiter()
        if self._task is not None:
            self._task.cancel()
            # a cancel landing while the loop sits on an already-completed
            # wait_for is swallowed (bpo-42130); wake it so the loop's own
            # _closed check at the top of the iteration still exits
            self._wake.set()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        err = RuntimeError("GenerationScheduler closed")
        for req in self._waiting:
            if not req.future.done():
                req.future.set_exception(err)
        self._waiting.clear()
        for _payload, fut in self._prefix_installs:
            if not fut.done():
                fut.set_exception(err)
        self._prefix_installs.clear()

    # ---------------------------------------------------------------- loop

    def _finish_tl(self, req: _Request) -> None:
        """Terminal timeline event for a completed request — called AFTER
        the block event that delivered its last token, so the event order
        reads admit -> blocks -> terminal."""
        self._end_tl(req, req.done_reason or "budget", tokens=len(req.out))

    def _complete(self, req: _Request) -> None:
        if not req.future.done():
            req.future.set_result(np.asarray(req.out, np.int32))
        if req.out and req.t0:
            dur = time.perf_counter() - req.t0
            m = DEFAULT_METRICS
            m.generated_tokens.labels(self.model.name).inc(len(req.out))
            if dur > 0:
                m.tokens_per_s.labels(self.model.name).set(len(req.out) / dur)

    def _token_done(self, req: _Request, tok: int) -> bool:
        if not req.out and req.t0:
            # first sampled token: the serving TTFT (queue wait + prefill
            # + the first decode fetch); later deliveries measure against
            # this for the inter-token-latency ledger
            req.t_first_token = time.perf_counter()
            req.t_last_tok = req.t_first_token
            ttft = req.t_first_token - req.t0
            RECORDER.record_stage(STAGE_TTFT, ttft)
            # exemplar-linked observation (SCT_METRICS_EXEMPLARS): the
            # bucket carries this request's trace id, so a p99 spike on
            # the /prometheus histogram links straight to its
            # GET /stats/timeline?trace= forensics
            from seldon_core_tpu.utils.metrics import observe_exemplar

            observe_exemplar(
                DEFAULT_METRICS.ttft.labels(self.model.name), ttft,
                req.timeline.trace_id if req.timeline is not None else None,
            )
            if req.span is not None:
                req.span.event("first-token", ttft_ms=round(ttft * 1e3, 3))
        req.out.append(tok)
        if req.on_token is not None:
            try:
                req.on_token(tok)
            except Exception:  # a broken listener must not stall the loop
                log.exception("on_token hook failed; detaching it")
                req.on_token = None
        if req.eos_id is not None and tok == req.eos_id:
            req.done_reason = "eos"
            return True
        if len(req.out) >= req.max_new_tokens:
            req.done_reason = "budget"
            return True
        return False

    def _reap_queues(self) -> None:
        """Pre-admission QoS sweep: drop abandoned requests (client gone →
        cancelled future) and fail expired ones with a 504 from the queue,
        BEFORE a prefill is spent on them."""
        now = time.monotonic()
        for q in (self._waiting, self._overflow):
            keep = []
            for req in q:
                if req.future.done():
                    continue  # cancelled before admission: nothing to undo
                if req.deadline is not None and now >= req.deadline:
                    req.future.set_exception(qos.DeadlineExceeded(
                        f"deadline expired after "
                        f"{time.perf_counter() - req.t0:.3f}s waiting in the "
                        "generation queue"
                    ))
                    DEFAULT_METRICS.qos_deadline_miss.labels(
                        self.model.name, "generation-queue"
                    ).inc()
                    qos.note_deadline_miss("generation-queue", req.priority)
                    if req.span is not None:
                        req.span.event(
                            "qos-drop", reason="deadline",
                            stage="generation-queue",
                        )
                    self._end_tl(req, "deadline-reap", stage="queue")
                    continue
                keep.append(req)
            q[:] = keep

    def _reap_slots(self, slots, active) -> int:
        """In-flight QoS sweep: a slot whose client vanished or whose
        deadline passed must stop consuming decode steps mid-generation.
        Returns the number of slots reaped — a host-side reap invalidates
        the device carry (the chip still thinks the slot is active), so the
        overlap pipeline must rebuild its next dispatch from host state."""
        reaped = 0
        now = time.monotonic()
        for i in range(len(slots)):
            req = slots[i]
            if req is None or not active[i]:
                continue
            expired = req.deadline is not None and now >= req.deadline
            if not expired and not req.future.done():
                continue
            if expired and not req.future.done():
                req.future.set_exception(qos.DeadlineExceeded(
                    f"deadline expired mid-generation after "
                    f"{len(req.out)} tokens"
                ))
                DEFAULT_METRICS.qos_deadline_miss.labels(
                    self.model.name, "decode"
                ).inc()
                qos.note_deadline_miss("decode", req.priority)
                if req.span is not None:
                    req.span.event("qos-drop", reason="deadline", stage="decode")
                self._end_tl(
                    req, "deadline-reap", stage="decode", tokens=len(req.out)
                )
            else:
                self._end_tl(
                    req, "disconnect", stage="decode", tokens=len(req.out)
                )
            slots[i] = None
            active[i] = False
            self.model.release_slot(i)
            reaped += 1
        return reaped

    def _deliver(self, toks_seq, act_seq, slots, cur, active) -> None:
        """Fan one fetched block's ``(k, S)`` tokens out to their requests.
        Completions here (eos / budget) are DEVICE-visible transitions —
        the chip flipped the slot inactive at the same step — so the device
        carry stays consistent and the overlap pipeline keeps running; the
        freed slot's blocks are only re-reserved at the next sync point."""
        S = len(slots)
        now = time.perf_counter()
        reqs = list(slots)  # completions below null the live entries
        counts = [0] * S
        # cascade confidence (docs/GRAPHS.md): the block's per-token top-2
        # logit margins, stashed by the same fetch that brought the tokens
        # — accumulated here per delivered token, zero extra syncs.
        # getattr: duck-typed stand-in models (tests) predate the signal.
        conf_seq = getattr(self.model, "last_conf_seq", None)
        if conf_seq is not None and conf_seq.shape != toks_seq.shape:
            conf_seq = None  # stale stash (shape mismatch): never misattribute
        for step_i in range(toks_seq.shape[0]):
            for i in range(S):
                if not act_seq[step_i, i] or slots[i] is None:
                    continue
                req = slots[i]
                tok = int(toks_seq[step_i, i])
                cur[i] = tok
                counts[i] += 1
                if conf_seq is not None:
                    req.conf_sum += float(conf_seq[step_i, i])
                    req.conf_n += 1
                if self._token_done(req, tok):
                    self._complete(req)
                    slots[i] = None
                    active[i] = False
                    self.model.release_slot(i)
        # per-slot inter-token latency: one sample per (block, slot) — the
        # delivery gap spread over the tokens it carried.  A prefill (or
        # anything else) stalling the pipeline between blocks inflates
        # every live slot's sample; TTFT and device-step never see it.
        # getattr: duck-typed stand-in models (tests) predate the ledger.
        note_itl = getattr(self.model, "note_itl", None)
        # timeline: one "block" event per (fetched block, slot) from the
        # ALREADY-fetched emitted mask — with speculation on it carries the
        # per-block draft/accept split (passes that ran vs tokens emitted),
        # host-side arithmetic only
        spec_d = getattr(self.model, "spec_draft", 0)
        tps = getattr(self.model, "_tps", 1)
        # per-adapter served-token ledger (docs/MULTITENANT.md); getattr:
        # duck-typed stand-in models predate multi-LoRA
        note_adapter = getattr(self.model, "note_adapter_tokens", None)
        # usage attribution (obs/metering.py): this fused block's measured
        # device seconds (stashed by step_k_fetch at the one host sync)
        # split across the slots it served BY TOKEN SHARE — a slot that
        # emitted 3 of the block's 12 tokens is charged 25% of the block.
        # getattr: duck-typed stand-in models predate the meter.
        block_s = float(getattr(self.model, "last_block_s", 0.0) or 0.0)
        block_tokens = sum(counts)
        if block_s and not block_tokens:
            # a block that emitted nothing (every slot went inactive at
            # dispatch) still spent the device: charge the base row so
            # attribution stays conservation-exact against the wall total
            METER.add(self.model.name, device_s=block_s)
        for i in range(S):
            req = reqs[i]
            if req is None or not counts[i]:
                continue
            if req.adapter and note_adapter is not None:
                note_adapter(req.adapter, counts[i])
            if req.t_last_tok and note_itl is not None:
                note_itl((now - req.t_last_tok) / counts[i])
            req.t_last_tok = now
            accepted = 0
            if spec_d and toks_seq.shape[0] % tps == 0:
                passes = int(
                    np.asarray(act_seq[:, i])
                    .reshape(-1, tps)
                    .any(axis=1)
                    .sum()
                )
                accepted = max(0, counts[i] - passes)
            share_s = (
                block_s * counts[i] / block_tokens if block_tokens else 0.0
            )
            req.u_device_s += share_s
            # per-proposer acceptance attribution (ISSUE 20 satellite):
            # the active spec_method is a build-time constant, so the
            # whole block's accepted tokens belong to one proposer row
            mkw = {}
            if accepted:
                m = getattr(self.model, "spec_method", None) or "ngram"
                mkw[f"tokens_spec_accepted_{m}"] = accepted
            METER.add(
                self.model.name, req.adapter or "", req.priority,
                device_s=share_s, tokens_decode=counts[i],
                tokens_spec_accepted=accepted,
                **mkw,
            )
            if req.timeline is not None or req.span is not None:
                attrs = {"tokens": counts[i]}
                if spec_d and toks_seq.shape[0] % tps == 0:
                    attrs.update(
                        passes=passes,
                        drafted=passes * spec_d,
                        accepted=accepted,
                    )
                self._tl(req, "block", **attrs)
            if slots[i] is None and req.done_reason is not None:
                # completed in this block: terminal AFTER its block event
                self._finish_tl(req)

    def _fail_inflight(self, slots, active, exc: BaseException) -> None:
        """A failed device step poisons every in-flight request,
        mid-prefill admissions included (their blocks release with the
        blanket slot sweep below)."""
        for ent in self._prefilling:
            if not ent["req"].future.done():
                ent["req"].future.set_exception(exc)
            self._end_tl(ent["req"], "error", stage="prefill")
        self._prefilling.clear()
        self._prefill_slots.clear()
        for i in range(len(slots)):
            if slots[i] is not None:
                if not slots[i].future.done():
                    slots[i].future.set_exception(exc)
                self._end_tl(slots[i], "error", stage="decode")
            slots[i] = None
            self.model.release_slot(i)
        active[:] = False

    async def _run(self) -> None:
        S = self.model.n_slots
        slots: list[_Request | None] = [None] * S
        cur = np.zeros(S, np.int32)
        temps = np.zeros(S, np.float32)
        active = np.zeros(S, bool)
        k = self.model.decode_block
        # overlapped pipeline state: the dispatched-but-unfetched block, and
        # whether the device carry still matches host bookkeeping (a reap or
        # admission makes the next dispatch rebuild from host arrays)
        pending: tuple | None = None
        carry_dirty = True
        try:
            while True:
                if self._closed:
                    # close() may have lost its cancel to a completed
                    # wait_for (bpo-42130); route through the same cleanup
                    raise asyncio.CancelledError
                self._reap_queues()
                self._reap_suspended()
                if pending is None and self._external_release:
                    # handoff slots released with no block in flight: safe
                    # to return their blocks to the pool right here
                    self._drain_external_releases()
                if pending is None and self._prefix_installs:
                    # peer-pulled chains: the install scatter takes pool
                    # blocks, legal only with no decode block in flight
                    await self._drain_prefix_installs()
                if pending is None:
                    # draft prefills deferred by the arbiter (batch-class
                    # registrant) run at this sync point, off the decode
                    # block's critical path
                    await self._drain_draft_prefills()
                if pending is None and self._preempt and active.any():
                    # preemption verb (docs/PACKING.md): at this sync
                    # point, export every active slot into the suspend
                    # store and free its blocks — the device carry no
                    # longer matches host bookkeeping afterwards
                    if await self._suspend_active(slots, cur, temps, active):
                        carry_dirty = True
                if pending is None and self._suspended and not self._preempt:
                    # resume verb: suspended records re-queue as imported
                    # admissions (donated fused-scatter path, bit-exact)
                    self._drain_resumes()
                if (
                    pending is None
                    and self._preempt
                    and not active.any()
                    and not self._prefilling
                ):
                    # preempted: the arbiter gave the device to a
                    # co-tenant — hold admissions (and the grant) until
                    # request_resume lifts the flag.  The timeout keeps
                    # deadline reaping of parked/suspended work at ~50ms
                    # granularity; spinning would starve the co-tenant's
                    # event-loop turns.
                    self._arb_release()
                    if self._draining and not self._quiesced.is_set():
                        # drain verb: nothing device-resident any more —
                        # every active slot is parked (or ran to completion
                        # when the store refused it); the migration's
                        # export half may proceed
                        self._quiesced.set()
                    for q in (self._waiting, self._overflow):
                        for r in q:
                            self._tl(
                                r, "paused", span=False, cause="preempted"
                            )
                    self._wake.clear()
                    if self._arbiter is not None:
                        # off-edge policy tick: with the interactive side
                        # gone quiet there may be no grant edge left to
                        # trigger our resume
                        self._arbiter.poll()
                    if not self._preempt:
                        continue
                    try:
                        await asyncio.wait_for(self._wake.wait(), timeout=0.05)
                    except asyncio.TimeoutError:
                        pass
                    continue
                if (
                    pending is None
                    and not active.any()
                    and not self._overflow
                    and not self._waiting
                    and not self._prefilling
                    and not self._prefix_installs
                ):
                    # fully idle: park until a submit wakes us (no await
                    # between the emptiness check and clear, so a submit
                    # landing now still sets the event we wait on).  The
                    # device grant goes back first — an idle co-tenant
                    # must never hold the chip.
                    self._arb_release()
                    self._wake.clear()
                    await self._wake.wait()
                    self._reap_queues()
                if pending is None:
                    # sync point: admissions and dispatch only happen with
                    # no block in flight — a prefill (or a freed block's
                    # reuse) must never race a dispatched decode.
                    # Admit whatever is waiting into remaining free slots —
                    # block-starved overflow first, then the wait list in
                    # (priority, arrival) order so batch traffic can never
                    # starve interactive; all prefills dispatch back-to-back
                    # and their first tokens are fetched in ONE device
                    # round trip
                    batch: list[_Request] = []
                    # capacity excludes slots pinned by in-flight handoffs
                    # and slots mid-chunked-prefill; a preempted scheduler
                    # admits NOTHING (its free blocks belong to the
                    # co-tenant until the arbiter resumes it)
                    cap_free = (
                        0
                        if self._preempt
                        else S - int(active.sum()) - len(self._external)
                        - len(self._prefill_slots)
                    )
                    # embed-only requests consume no slot or KV: the whole
                    # waiting wave serves this sync point regardless of
                    # cap_free (a preempted scheduler holds them — the
                    # device belongs to the co-tenant)
                    embeds: list[_Request] = []
                    if not self._preempt:
                        embeds = [r for r in self._waiting if r.embed_only]
                        for r in embeds:
                            self._waiting.remove(r)
                    while self._overflow and len(batch) < cap_free:
                        batch.append(self._overflow.pop(0))
                    if self._waiting and len(batch) < cap_free:
                        self._waiting.sort(
                            key=lambda r: (qos.priority_rank(r.priority), r.t0)
                        )
                        while self._waiting and len(batch) < cap_free:
                            batch.append(self._waiting.pop(0))
                    if batch or embeds or self._prefilling or active.any():
                        # packed chip (docs/PACKING.md): all device work
                        # below — prefills, chunk advances, the fused
                        # block dispatch — runs under the device grant;
                        # a co-tenant's block never interleaves inside it
                        await self._arb_acquire()
                    if embeds:
                        await self._admit_embeds(embeds)
                    if batch:
                        await self._admit_batch(batch, slots, cur, temps, active)
                    if self._prefilling:
                        # chunked prefill: ONE chunk per sync point — the
                        # admission cost a decode stall can see is bounded
                        # by a chunk, not a prompt (docs/PERFORMANCE.md §7)
                        await self._advance_prefill(slots, cur, temps, active)
                    self._reap_slots(slots, active)
                    if not active.any():
                        # nothing to dispatch: the grant goes back before
                        # any park or spin below
                        self._arb_release()
                        if self._prefilling:
                            # chunks still advancing: loop straight back —
                            # each iteration does real device work
                            continue
                        if self._overflow and not self._external:
                            # nothing in flight can ever free blocks: these
                            # requests exceed the pool outright
                            err = GraphUnitError(
                                "request KV reservation exceeds the configured "
                                f"pool ({self.model.kv_blocks - 1} blocks of "
                                f"{self.model.kv_block_size})"
                            )
                            for req in self._overflow:
                                if not req.future.done():
                                    req.future.set_exception(err)
                            self._overflow.clear()
                        elif (
                            (self._overflow or self._waiting)
                            and self._external
                            and not self._external_release
                        ):
                            # every admittable slot (or the blocks) is
                            # pinned by an in-flight handoff: park until a
                            # release or submit wakes us — spinning here
                            # would monopolize the event loop and starve
                            # the very release callback we wait for.  The
                            # timeout keeps deadline reaping of parked
                            # queue entries at ~50ms granularity.
                            for q in (self._waiting, self._overflow):
                                for r in q:
                                    # deduped repeat on the timeline; never
                                    # folded onto the span (a long park
                                    # would flood it)
                                    self._tl(
                                        r, "paused", span=False,
                                        cause="externals-pinned",
                                    )
                            self._wake.clear()
                            try:
                                await asyncio.wait_for(
                                    self._wake.wait(), timeout=0.05
                                )
                            except asyncio.TimeoutError:
                                pass
                        continue
                    seed = self._next_seed()
                    if k <= 1:
                        # single-step path (decode_block=1): dispatch, fetch
                        # and deliver inline — no fused block to overlap
                        try:
                            toks = await asyncio.to_thread(
                                self.model.step, cur, active, temps, seed
                            )
                        except asyncio.CancelledError:
                            raise
                        except Exception as exc:
                            log.exception(
                                "decode step failed; failing %d in-flight requests",
                                int(active.sum()),
                            )
                            self._arb_release()
                            self._fail_inflight(slots, active, exc)
                            continue
                        self._deliver(toks[None], active.copy()[None], slots, cur, active)
                        self._reap_slots(slots, active)
                        # single-step path: every step IS a sync point, so
                        # the grant rotates per step on a packed chip
                        self._arb_release()
                        continue
                    # one dispatch yields up to k tokens per slot; the
                    # device enforces per-slot eos + budget so finished
                    # slots stop touching the cache mid-block
                    eos = np.array(
                        [
                            slots[i].eos_id
                            if slots[i] is not None and slots[i].eos_id is not None
                            else -1
                            for i in range(S)
                        ],
                        np.int32,
                    )
                    remaining = np.array(
                        [
                            max(0, slots[i].max_new_tokens - len(slots[i].out))
                            if slots[i] is not None
                            else 0
                            for i in range(S)
                        ],
                        np.int32,
                    )
                    try:
                        pending = await asyncio.to_thread(
                            self.model.step_k_dispatch,
                            cur, active, temps, seed, eos, remaining, k,
                        )
                    except asyncio.CancelledError:
                        raise
                    except Exception as exc:
                        log.exception(
                            "decode dispatch failed; failing %d in-flight requests",
                            int(active.sum()),
                        )
                        self._arb_release()
                        self._fail_inflight(slots, active, exc)
                        continue
                    carry_dirty = False
                    continue
                # fetch phase — THE overlap: while block N's results are in
                # flight, dispatch block N+1 straight from the on-device
                # carry, so the chip starts the next block before the host
                # has even seen this one.  Only in steady state: waiting
                # work needs a sync point (admission), and a dirty carry
                # (host-side reap) must be rebuilt from host arrays.
                nxt: tuple | None = None
                break_cause: str | None = None
                if self.overlap and active.any():
                    # the overlap pipeline only continues from the device
                    # carry in steady state; name WHY it breaks (the cause
                    # lands on every live stream's timeline — the forensics
                    # for "this request's ITL spiked right here")
                    if carry_dirty:
                        break_cause = "carry-dirty"
                    elif self._preempt or self._arb_contended():
                        # packed chip: a co-tenant wants (or was granted)
                        # the device — yield at the block boundary instead
                        # of chaining another block off the carry
                        break_cause = "arbiter-yield"
                    elif self._waiting:
                        break_cause = "admission"
                    elif self._overflow:
                        break_cause = "kv-starved"
                    elif self._external_release:
                        break_cause = "handoff-release"
                    elif self._prefilling:
                        break_cause = "chunked-prefill"
                if self.overlap and active.any() and break_cause is None:
                    try:
                        nxt = await asyncio.to_thread(
                            self.model.step_k_continue, active, self._next_seed(), k
                        )
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        log.exception(
                            "overlapped dispatch failed; falling back to sequential"
                        )
                        nxt = None
                        carry_dirty = True
                        break_cause = "dispatch-error"
                if break_cause is not None:
                    for i in range(S):
                        if slots[i] is not None and active[i]:
                            self._tl(
                                slots[i], "overlap-break", cause=break_cause
                            )
                try:
                    toks_seq, act_seq = await asyncio.to_thread(
                        self.model.step_k_fetch, pending
                    )
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    log.exception(
                        "decode step failed; failing %d in-flight requests",
                        int(active.sum()),
                    )
                    if nxt is not None:
                        # drain the speculative block too (its carry chained
                        # off the failed one; a dangling fetch helps nobody)
                        try:
                            await asyncio.to_thread(self.model.step_k_fetch, nxt)
                        except Exception:
                            pass
                    pending = None
                    carry_dirty = True
                    self._arb_release()
                    self._fail_inflight(slots, active, exc)
                    continue
                pending = nxt
                if pending is None:
                    # pipeline drained to a sync point: rotate the grant
                    # BEFORE host-side delivery so a parked co-tenant's
                    # dispatch overlaps our bookkeeping
                    self._arb_release()
                self._deliver(toks_seq, act_seq, slots, cur, active)
                if self._reap_slots(slots, active):
                    # host-side reap: the chip still thinks those slots are
                    # live — the next dispatch must rebuild from host state
                    carry_dirty = True
        except asyncio.CancelledError:
            err = RuntimeError("GenerationScheduler closed")
            for ent in self._prefilling:
                if not ent["req"].future.done():
                    ent["req"].future.set_exception(err)
                self._end_tl(ent["req"], "error", cause="closed")
            self._prefilling.clear()
            self._prefill_slots.clear()
            for i, req in enumerate(slots):
                if req is not None:
                    if not req.future.done():
                        req.future.set_exception(err)
                    self._end_tl(req, "error", cause="closed")
                self.model.release_slot(i)
            for req in self._overflow:
                if not req.future.done():
                    req.future.set_exception(err)
                self._end_tl(req, "error", cause="closed")
            self._overflow.clear()
            for rec in self._suspended:
                self._meter_unpark(rec)
                if not rec["req"].future.done():
                    rec["req"].future.set_exception(err)
                self._end_tl(rec["req"], "error", cause="closed")
            self._suspended.clear()
            if self._suspend_store is not None:
                self._suspend_store.flush()
            self._arb_release()
            raise

    async def _admit_batch(self, batch, slots, cur, temps, active) -> None:
        free = [
            i
            for i in range(len(slots))
            if not active[i]
            and i not in self._external
            and i not in self._prefill_slots
        ]
        # chunk-pace an admission only when live decode streams exist to
        # protect: an idle scheduler admits monolithically — nothing can
        # stall, the prefill costs fewer dispatches, and sampled streams
        # keep the exact seed-per-block sequence of the unchunked path.
        # getattr: duck-typed stand-in models (tests) predate chunking.
        chunk_c = (
            getattr(self.model, "prefill_chunk", 0) if active.any() else 0
        )

        def dispatch_and_fetch():
            placed = []
            errors = []
            starved = []
            chunked = []
            for req, slot in zip(batch, free):
                # duck-typed stand-in models (tests) predate multi-LoRA:
                # only pass the kwarg when the request actually names one
                akw = {"adapter": req.adapter} if req.adapter else {}
                try:
                    if req.imported is not None:
                        # disagg import: the prompt KV arrived from a
                        # prefill engine — reserve + scatter, no prefill.
                        # A resumed suspend record (docs/PACKING.md) rides
                        # the same path with its EXTENDED prompt (original
                        # prompt + tokens emitted before suspension) and
                        # the frame's remaining-token reservation.
                        imp = req.imported
                        # spec kwarg only when a state rode the frame:
                        # duck-typed stand-in models predate speculation
                        skw = (
                            {"spec_state": imp["spec"]}
                            if imp.get("spec") is not None
                            else {}
                        )
                        self.model.attach_imported(
                            slot, imp.get("prompt", req.prompt),
                            imp["k"], imp["v"],
                            reserve_tokens=int(
                                imp.get("reserve_tokens", req.max_new_tokens)
                            ),
                            k_scale=imp.get("k_scale"),
                            v_scale=imp.get("v_scale"),
                            first_token=imp["first_token"],
                            **akw, **skw,
                        )
                        placed.append((req, slot, imp["first_token"]))
                        continue
                    if (
                        chunk_c
                        and not req.prefill_only
                        and req.prompt.size > chunk_c
                    ):
                        # chunked prefill: reserve only (host-side) — the
                        # run loop paces the chunks, one per sync point
                        plan = self.model.admit_chunk_plan(
                            slot, req.prompt, req.temperature,
                            self._next_seed(),
                            reserve_tokens=req.max_new_tokens,
                            **akw,
                        )
                        chunked.append((req, slot, plan))
                        continue
                    tok_dev = self.model.admit_dispatch(
                        slot, req.prompt, req.temperature, self._next_seed(),
                        reserve_tokens=req.max_new_tokens,
                        **akw,
                    )
                    placed.append((req, slot, tok_dev))
                except OutOfKVBlocks:
                    # pool is momentarily full: hold until completions free
                    # blocks (the run loop fails it if nothing is in flight)
                    starved.append(req)
                except Exception as exc:  # noqa: BLE001 - routed to the future
                    errors.append((req, exc))
            # one round trip fetches every admitted first token (imported
            # first tokens are host ints already; device_get passes them)
            # one round trip per admitted batch, not per token
            # sct: host-sync-ok admission sync point
            toks = jax.device_get([t for _, _, t in placed]) if placed else []
            return placed, toks, errors, starved, chunked

        placed, toks, errors, starved, chunked = await asyncio.to_thread(
            dispatch_and_fetch
        )
        # timeline admit events come from host-side reservation bookkeeping
        # (reuse depth, block split) — getattr: stand-in models predate it
        resnap = getattr(self.model, "reservation_snapshot", lambda s: None)
        # stamp the active proposer on admit events so a timeline reader
        # can attribute acceptance-rate shifts to the speculation config
        specm = getattr(self.model, "spec_method", None)
        smkw = {"spec_method": specm} if specm else {}
        for req, slot, plan in chunked:
            if req.future.done():  # client vanished while we reserved
                self.model.release_slot(slot)
                self._end_tl(req, "disconnect", stage="prefill")
                continue
            self._note_queue_wait(req)
            self._prefilling.append(
                {"req": req, "slot": slot, "plan": plan, "i": 0}
            )
            self._prefill_slots.add(slot)
            akw = {"adapter": req.adapter} if req.adapter else {}
            snap = resnap(slot) or {}
            self._meter_admit(req, snap)
            self._tl(
                req, "admit", slot=slot, chunked=True,
                chunks=len(plan["payloads"]), **akw, **snap, **smkw,
            )
        for req in starved:
            self._tl(req, "kv-starved", span=False)
        self._overflow.extend(starved)
        for req, exc in errors:
            if not isinstance(exc, GraphUnitError):
                log.exception("prefill admission failed", exc_info=exc)
            if not req.future.done():
                req.future.set_exception(exc)
            self._end_tl(req, "error", stage="admit")
        for (req, slot, _), tok in zip(placed, toks):
            if req.prefill_only:
                # disagg handoff: pin the slot (blocks stay reserved for
                # the KV export) and hand (slot, first_token) back; a
                # client that vanished mid-prefill releases immediately
                if req.future.done():
                    self.model.release_slot(slot)
                    self._end_tl(req, "disconnect", stage="prefill")
                else:
                    self._external.add(slot)
                    akw = {"adapter": req.adapter} if req.adapter else {}
                    snap = resnap(slot) or {}
                    self._meter_admit(req, snap)
                    self._tl(
                        req, "admit", slot=slot, prefill_only=True,
                        **akw, **snap, **smkw,
                    )
                    req.future.set_result((slot, int(tok)))
                    self._end_tl(req, "exported", slot=slot)
                continue
            self._note_queue_wait(req)
            attrs = resnap(slot) or {}
            if req.imported is None:
                # imported admissions (disagg handoff / resumed suspends)
                # prefilled nothing here — the paying engine metered it
                self._meter_admit(req, attrs)
            if req.adapter:
                attrs["adapter"] = req.adapter
            if req.imported is not None and req.imported.get("resumed"):
                # resumed suspend record (docs/PACKING.md): the carry
                # token was already delivered to the client before the
                # suspension — running it through _token_done again would
                # double-deliver it.  Re-arm the slot directly; the
                # remaining-token budget derives from len(out) as usual.
                req.imported = None  # free the record's host arrays
                req.t_last_tok = time.perf_counter()  # ITL skips the gap
                self._tl(
                    req, EVENT_RESUME, slot=slot, tokens=len(req.out),
                    **attrs,
                )
                slots[slot] = req
                cur[slot] = int(tok)
                temps[slot] = req.temperature
                active[slot] = True
                continue
            if req.imported is not None:
                attrs["imported"] = True
            self._tl(req, "admit", slot=slot, **attrs, **smkw)
            if self._token_done(req, int(tok)):
                self._complete(req)
                self._finish_tl(req)
                self.model.release_slot(slot)
                continue
            slots[slot] = req
            cur[slot] = int(tok)
            temps[slot] = req.temperature
            active[slot] = True

    async def _advance_prefill(self, slots, cur, temps, active) -> None:
        """Advance chunked prefills by ONE chunk (Sarathi-style stall-free
        admission, docs/PERFORMANCE.md §7).  Runs only at sync points, so a
        chunk and a decode block are queued back-to-back on the device and
        the in-flight streams pay at most one chunk of extra latency per
        block.  Intermediate chunks are dispatched without a host fetch;
        only the final chunk's sampled token is materialized — the same one
        host sync an unchunked admission costs."""
        now = time.monotonic()
        keep = []
        for ent in self._prefilling:
            req = ent["req"]
            if req.future.done():  # cancel-on-disconnect mid-prefill
                self._prefill_slots.discard(ent["slot"])
                self.model.release_slot(ent["slot"])
                self._end_tl(req, "disconnect", stage="prefill", chunks=ent["i"])
                continue
            if req.deadline is not None and now >= req.deadline:
                req.future.set_exception(qos.DeadlineExceeded(
                    f"deadline expired after {ent['i']} prefill chunks"
                ))
                DEFAULT_METRICS.qos_deadline_miss.labels(
                    self.model.name, "prefill"
                ).inc()
                qos.note_deadline_miss("prefill", req.priority)
                if req.span is not None:
                    req.span.event(
                        "qos-drop", reason="deadline", stage="prefill"
                    )
                self._prefill_slots.discard(ent["slot"])
                self.model.release_slot(ent["slot"])
                self._end_tl(
                    req, "deadline-reap", stage="prefill", chunks=ent["i"]
                )
                continue
            keep.append(ent)
        self._prefilling[:] = keep
        if not self._prefilling:
            return
        ent = self._prefilling[0]
        req, slot, plan = ent["req"], ent["slot"], ent["plan"]
        last = ent["i"] == len(plan["payloads"]) - 1

        def one_chunk():
            tok_dev = self.model.prefill_chunk_dispatch(plan, ent["i"])
            return int(tok_dev) if last else None

        try:
            tok = await asyncio.to_thread(one_chunk)
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            if not isinstance(exc, GraphUnitError):
                log.exception("chunked prefill failed")
            self._prefilling.pop(0)
            self._prefill_slots.discard(slot)
            self.model.release_slot(slot)
            if not req.future.done():
                req.future.set_exception(exc)
            self._end_tl(req, "error", stage="prefill", chunks=ent["i"])
            return
        self._tl(
            req, "chunk", i=ent["i"], of=len(plan["payloads"]), last=last
        )
        ent["i"] += 1
        if not last:
            return
        self._prefilling.pop(0)
        self._prefill_slots.discard(slot)
        if self._token_done(req, tok):
            self._complete(req)
            self._finish_tl(req)
            self.model.release_slot(slot)
            return
        slots[slot] = req
        cur[slot] = tok
        temps[slot] = req.temperature
        active[slot] = True


PAD_ID = -1  # right-pad for ragged generated rows in dense responses

_STREAM_END = object()  # queue sentinel: the submit task completed


class GenerativeComponent(SeldonComponent):
    """Graph unit serving a generative decoder.

    Wire contract (MODEL unit, ``predict``):

    * ``data.ndarray`` (B, L) int token ids -> (B, <=max_new) generated ids,
      rows right-padded with ``-1`` where EOS ended a row early;
    * ``strData`` JSON ``{"tokens": [[...], ...] | [...],
      "max_new_tokens": N, "temperature": t, "eos_id": e}`` ->
      ``strData`` JSON ``{"tokens": [[...], ...]}`` — per-request options.
    """

    # metrics() exposes cumulative step counters only (safe to race);
    # serializing would defeat continuous batching
    SAFE_ANNOTATIONS = True

    def __init__(
        self,
        model: GenerativeModel,
        *,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        eos_id: int | None = None,
        queue_max: int | None = None,
        overlap: bool | None = None,
        adapter: str | None = None,
        pack_class: str | None = None,
        pack_slo_ms: float | None = None,
    ):
        self.model = model
        self.scheduler = GenerationScheduler(
            model, maxsize=queue_max, overlap=overlap
        )
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        # greedy decode is a pure function of the prompt, so a temperature-0
        # deployment participates in the caching plane (exact + semantic
        # response tiers both gate on whole-graph determinism); sampled
        # decode (default temperature > 0) draws from a per-process seed and
        # must never be cached.  Per-request temperature overrides are safe:
        # cache keys cover the full body, so an override that turns sampling
        # on can at worst replay its own first sample, never another
        # request's bytes.  Instance-level on purpose — the walker reads the
        # flag per component.
        self.DETERMINISTIC = self.temperature == 0.0
        self.eos_id = eos_id
        # deployment-default LoRA adapter (docs/MULTITENANT.md): requests
        # may override per call with the strData "adapter" field; the A/B
        # and canary machinery splits traffic between two adapter ids of
        # one base deployment by giving each predictor a different default
        self.adapter = adapter or None
        # chip packing (docs/PACKING.md): this deployment's QoS class and
        # queue-wait SLO band on a packed device.  Registration with the
        # process arbiter is explicit (register_packed / the engine's
        # multi-deployment boot) or via SCT_PACK=1 — a sole-tenant
        # deployment never touches the arbiter.
        self.pack_class = (
            qos.parse_priority(pack_class) if pack_class else None
        )
        self.pack_slo_ms = float(pack_slo_ms) if pack_slo_ms else None
        if os.environ.get("SCT_PACK", "0") == "1":
            self.register_packed()

    def register_packed(self, arbiter=None) -> None:
        """Attach this deployment's scheduler to the device arbiter
        (process-wide one by default) under its packing class/SLO."""
        if self.scheduler._arbiter is not None:
            return
        if arbiter is None:
            from seldon_core_tpu.executor.arbiter import get_arbiter

            arbiter = get_arbiter()
        self.scheduler.attach_arbiter(
            arbiter,
            priority=self.pack_class or qos.PRIO_INTERACTIVE,
            slo_ms=self.pack_slo_ms,
        )

    def warmup(self) -> int:
        return self.model.warmup()

    def warmup_variants(self) -> list[str]:
        """Per-(bucket, program) compile attribution for /stats/warmup —
        names the speculative-verify and int8 variants explicitly so
        readiness provably covered every program actually served."""
        return list(self.model.warmup_programs)

    async def close(self) -> None:
        await self.scheduler.close()
        self.model.release_memory()

    def metrics(self) -> list[dict[str, Any]]:
        out = [
            {"key": f"{self.model.name}_decode_steps", "type": "GAUGE", "value": self.model.steps},
            {"key": f"{self.model.name}_prefills", "type": "GAUGE", "value": self.model.prefills},
            {"key": f"{self.model.name}_overlapped_blocks", "type": "GAUGE", "value": self.model.overlapped},
            {"key": f"{self.model.name}_kv_imports", "type": "GAUGE", "value": self.model.imports},
        ]
        if self.model.spec_draft and self.model.spec_verify_passes:
            out.append({
                "key": f"{self.model.name}_accepted_tokens_per_step",
                "type": "GAUGE",
                "value": self.model.spec_emitted_tokens
                / self.model.spec_verify_passes,
            })
        if self.model.prefix_index is not None:
            out.append({
                "key": f"{self.model.name}_prefills_reused",
                "type": "GAUGE",
                "value": self.model.prefills_reused,
            })
        if self.model.prefill_chunk:
            out.append({
                "key": f"{self.model.name}_prefill_chunks",
                "type": "GAUGE",
                "value": self.model.prefill_chunks,
            })
        if self.model.embed_enabled or self.model.embeds:
            out.append({
                "key": f"{self.model.name}_embeds",
                "type": "GAUGE",
                "value": self.model.embeds,
            })
        return out

    async def _generate_rows(
        self,
        rows: list[np.ndarray],
        max_new_tokens: int,
        temperature: float,
        eos_id: int | None,
        adapter: str | None = None,
        infos: list[dict] | None = None,
    ) -> list[np.ndarray]:
        if infos is not None:
            infos.clear()
            infos.extend({} for _ in rows)
        return list(
            await asyncio.gather(
                *(
                    self.scheduler.submit(
                        row,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature,
                        eos_id=eos_id,
                        adapter=adapter,
                        info=infos[i] if infos is not None else None,
                    )
                    for i, row in enumerate(rows)
                )
            )
        )

    async def embed_rows(self, rows: list[np.ndarray]) -> np.ndarray:
        """Mean-pooled final hidden states for a batch of prompts — the
        /embeddings serving path (docs/GRAPHS.md): each row rides the
        scheduler's bounded intake and QoS pops, the run loop serves the
        wave with one device sync.  Returns (B, E) float32."""
        outs = await asyncio.gather(
            *(self.scheduler.submit_embed(row) for row in rows)
        )
        return np.stack([np.asarray(o, np.float32) for o in outs])

    @staticmethod
    def _pad_rows(outs: list[np.ndarray]) -> np.ndarray:
        width = max((o.size for o in outs), default=0)
        dense = np.full((len(outs), width), PAD_ID, np.int32)
        for i, o in enumerate(outs):
            dense[i, : o.size] = o
        return dense

    async def predict(self, X: np.ndarray, names: list[str]) -> np.ndarray:
        X = np.atleast_2d(np.asarray(X))
        if not np.issubdtype(X.dtype, np.integer):
            if not np.all(np.equal(np.mod(X, 1), 0)):
                raise GraphUnitError("generative input must be integer token ids")
            X = X.astype(np.int32)
        # rows of a dense batch may carry our own PAD_ID right-padding
        # (e.g. a previous response fed back): strip it per row
        rows = []
        for row in X:
            row = np.asarray(row, np.int32)
            keep = row != PAD_ID
            rows.append(row[: int(keep.cumsum().argmax()) + 1] if keep.any() else row)
        outs = await self._generate_rows(
            rows, self.max_new_tokens, self.temperature, self.eos_id,
            self.adapter,
        )
        return self._pad_rows(outs)

    async def stream(
        self,
        prompt: np.ndarray,
        *,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        eos_id: int | None = None,
        adapter: str | None = None,
    ) -> AsyncIterator[int]:
        """Yield generated token ids as they decode (the streaming serving
        path — neither the reference nor its successor streams at all).

        Tokens surface ``decode_block`` at a time per device fetch: deploy
        with a small block (e.g. 4-8) when time-to-first-token matters, the
        default large block when bulk throughput does.
        """
        q: asyncio.Queue = asyncio.Queue()
        task = asyncio.create_task(
            self.scheduler.submit(
                np.asarray(prompt, np.int32).ravel(),
                max_new_tokens=(
                    self.max_new_tokens if max_new_tokens is None else max_new_tokens
                ),
                temperature=(
                    self.temperature if temperature is None else temperature
                ),
                eos_id=self.eos_id if eos_id is None else eos_id,
                adapter=self.adapter if adapter is None else (adapter or None),
                on_token=q.put_nowait,
            )
        )
        task.add_done_callback(lambda t: q.put_nowait(_STREAM_END))
        served = 0
        try:
            while True:
                item = await q.get()
                if item is _STREAM_END:
                    break
                served += 1
                yield int(item)
            # surface a failed submit (bad prompt, closed scheduler) —
            # and tokens the hook delivered between our last get and the
            # sentinel
            result = task.result()
            for tok in result[served:]:
                yield int(tok)
        finally:
            if not task.done():
                task.cancel()

    async def predict_raw(self, p):
        from seldon_core_tpu.contract.payload import DataKind, Payload

        if p.kind != DataKind.STRING:
            arr = await self.predict(p.array, p.names)
            return p.with_array(arr, names=[])
        try:
            body = json.loads(p.data)
            tokens = body["tokens"]
            if not isinstance(tokens, (list, tuple)):
                raise TypeError("'tokens' must be a list")
            single = bool(tokens) and not isinstance(tokens[0], (list, tuple))
            rows = [np.asarray(tokens, np.int32)] if single else [
                np.asarray(r, np.int32) for r in tokens
            ]
        except (json.JSONDecodeError, TypeError, KeyError, ValueError) as e:
            raise GraphUnitError(f"bad generative request: {e}") from e
        eos = body.get("eos_id", self.eos_id)
        adapter = body.get("adapter", self.adapter)
        # cascade routing (docs/GRAPHS.md): with the on-device confidence
        # signal compiled in, every strData response carries the per-row
        # mean top-2 logit margin — the router reads it from the child's
        # reply, so the payload a non-escalated request returns stays
        # byte-identical to calling the tier directly (tokens unchanged,
        # confidence additive)
        infos: list[dict] | None = [] if self.model.conf_signal else None
        outs = await self._generate_rows(
            rows,
            int(body.get("max_new_tokens", self.max_new_tokens)),
            float(body.get("temperature", self.temperature)),
            int(eos) if eos is not None else None,
            str(adapter) if adapter else None,
            infos=infos,
        )
        result = [o.tolist() for o in outs]
        reply: dict = {"tokens": result[0] if single else result}
        if infos is not None:
            confs = [i.get("confidence") for i in infos]
            reply["confidence"] = confs[0] if single else confs
        return Payload(
            json.dumps(reply),
            [],
            DataKind.STRING,
            p.meta,
        )
