"""Contract testers: local microservice (pre-deploy) and gateway API
(post-deploy).

Reference counterparts: wrappers/testing/tester.py:137-200 (REST form-POST /
gRPC Model.Predict at a wrapped model) and util/api_tester/api-tester.py:
133-196 (OAuth client-credentials token, then authenticated predictions
through the gateway).  Differences by design: asyncio + pooled connections,
seeded generation, target validation, latency percentiles, and non-zero
exit codes on failure.
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import sys
import time

import numpy as np

from seldon_core_tpu.testing.contract import Contract


@dataclasses.dataclass
class TestReport:
    requests: int = 0
    failures: list[str] = dataclasses.field(default_factory=list)
    latencies_ms: list[float] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.requests > 0 and not self.failures

    def summary(self) -> dict:
        lat = np.asarray(self.latencies_ms) if self.latencies_ms else np.zeros(1)
        return {
            "requests": self.requests,
            "failures": len(self.failures),
            "ok": self.ok,
            "p50_ms": round(float(np.percentile(lat, 50)), 3),
            "p95_ms": round(float(np.percentile(lat, 95)), 3),
        }


def _rest_request(batch: np.ndarray, names: list[str], tensor: bool) -> dict:
    if tensor:
        data = {
            "names": names,
            "tensor": {"shape": list(batch.shape), "values": batch.ravel().tolist()},
        }
    else:
        data = {"names": names, "ndarray": batch.tolist()}
    return {"meta": {}, "data": data}


class MicroserviceTester:
    """Random-batch tester for a locally-running wrapped model."""

    def __init__(
        self,
        contract: Contract,
        host: str,
        port: int,
        *,
        tensor: bool = False,
        grpc: bool = False,
        endpoint: str = "predict",
        seed: int = 0,
        show: bool = False,
    ):
        self.contract = contract.unfold()
        self.host, self.port = host, port
        self.tensor, self.grpc = tensor, grpc
        self.endpoint = endpoint
        self.rng = np.random.default_rng(seed)
        self.show = show

    async def run(self, n_requests: int = 1, batch_size: int = 1) -> TestReport:
        report = TestReport()
        send = self._send_grpc if self.grpc else self._send_rest
        for _ in range(n_requests):
            batch = self.contract.generate_batch(batch_size, self.rng)
            t0 = time.perf_counter()
            try:
                body = await send(batch)
            except Exception as e:
                report.requests += 1
                report.failures.append(f"{type(e).__name__}: {e}")
                continue
            report.latencies_ms.append((time.perf_counter() - t0) * 1000.0)
            report.requests += 1
            if self.show:
                print(json.dumps(body)[:2000])
            report.failures.extend(
                self.contract.validate_response(body, batch.shape[0])
            )
        return report

    async def _send_rest(self, batch: np.ndarray) -> dict:
        import aiohttp

        req = _rest_request(batch, self.contract.feature_names(), self.tensor)
        url = f"http://{self.host}:{self.port}/{self.endpoint}"
        async with aiohttp.ClientSession() as s:
            async with s.post(url, json=req) as resp:
                return await resp.json()

    async def _send_grpc(self, batch: np.ndarray) -> dict:
        import grpc
        from google.protobuf import json_format

        from seldon_core_tpu.contract import Payload, payload_to_proto
        from seldon_core_tpu.contract.payload import DataKind
        from seldon_core_tpu.proto.grpc_defs import Stub

        kind = DataKind.TENSOR if self.tensor else DataKind.NDARRAY
        msg = payload_to_proto(
            Payload.from_array(batch, names=self.contract.feature_names(), kind=kind)
        )
        async with grpc.aio.insecure_channel(f"{self.host}:{self.port}") as ch:
            reply = await Stub(ch, "Model").Predict(msg, timeout=30.0)
        return json_format.MessageToDict(reply)


class ApiTester:
    """Deployed-API tester: OAuth token + authenticated predictions/feedback
    through the gateway (REST or gRPC)."""

    def __init__(
        self,
        contract: Contract,
        host: str,
        port: int,
        oauth_key: str,
        oauth_secret: str,
        *,
        tensor: bool = False,
        grpc: bool = False,
        grpc_port: int | None = None,
        endpoint: str = "predictions",
        seed: int = 0,
        show: bool = False,
    ):
        self.contract = contract.unfold()
        self.host, self.port = host, port
        self.oauth_key, self.oauth_secret = oauth_key, oauth_secret
        self.tensor, self.grpc = tensor, grpc
        self.grpc_port = grpc_port or port
        self.endpoint = endpoint
        self.rng = np.random.default_rng(seed)
        self.show = show

    async def get_token(self) -> str:
        import aiohttp

        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://{self.host}:{self.port}/oauth/token",
                data={"grant_type": "client_credentials"},
                auth=aiohttp.BasicAuth(self.oauth_key, self.oauth_secret),
            ) as resp:
                body = await resp.json()
                if "access_token" not in body:
                    raise RuntimeError(f"token request failed: {body}")
                return body["access_token"]

    def _request_body(self, batch: np.ndarray) -> dict:
        req = _rest_request(batch, self.contract.feature_names(), self.tensor)
        if self.endpoint == "feedback":
            return {"request": req, "reward": 1.0}
        return req

    async def run(self, n_requests: int = 1, batch_size: int = 1) -> TestReport:
        report = TestReport()
        token = await self.get_token()
        send = self._send_grpc if self.grpc else self._send_rest
        for _ in range(n_requests):
            batch = self.contract.generate_batch(batch_size, self.rng)
            t0 = time.perf_counter()
            try:
                body = await send(batch, token)
            except Exception as e:
                report.requests += 1
                report.failures.append(f"{type(e).__name__}: {e}")
                continue
            report.latencies_ms.append((time.perf_counter() - t0) * 1000.0)
            report.requests += 1
            if self.show:
                print(json.dumps(body)[:2000])
            if self.endpoint == "predictions":
                report.failures.extend(
                    self.contract.validate_response(body, batch.shape[0])
                )
            elif body.get("status", {}).get("status") not in (None, "SUCCESS"):
                report.failures.append(f"feedback failed: {body.get('status')}")
        return report

    async def _send_rest(self, batch: np.ndarray, token: str) -> dict:
        import aiohttp

        url = f"http://{self.host}:{self.port}/api/v0.1/{self.endpoint}"
        async with aiohttp.ClientSession() as s:
            async with s.post(
                url,
                json=self._request_body(batch),
                headers={"Authorization": f"Bearer {token}"},
            ) as resp:
                return await resp.json()

    async def _send_grpc(self, batch: np.ndarray, token: str) -> dict:
        import grpc
        from google.protobuf import json_format

        from seldon_core_tpu.contract import Payload, payload_to_proto
        from seldon_core_tpu.contract.payload import DataKind
        from seldon_core_tpu.gateway.grpc_gateway import OAUTH_METADATA_KEY
        from seldon_core_tpu.proto.grpc_defs import Stub

        kind = DataKind.TENSOR if self.tensor else DataKind.NDARRAY
        msg = payload_to_proto(
            Payload.from_array(batch, names=self.contract.feature_names(), kind=kind)
        )
        metadata = ((OAUTH_METADATA_KEY, token),)
        async with grpc.aio.insecure_channel(f"{self.host}:{self.grpc_port}") as ch:
            reply = await Stub(ch, "Seldon").Predict(msg, timeout=30.0, metadata=metadata)
        return json_format.MessageToDict(reply)


# ---------------------------------------------------------------------------
# CLIs
# ---------------------------------------------------------------------------

def _common_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("contract", help="contract.json path")
    parser.add_argument("host")
    parser.add_argument("port", type=int)
    parser.add_argument("-b", "--batch-size", type=int, default=1)
    parser.add_argument("-n", "--n-requests", type=int, default=1)
    parser.add_argument("--grpc", action="store_true")
    parser.add_argument("-t", "--tensor", action="store_true")
    parser.add_argument("-p", "--prnt", action="store_true", help="print responses")
    parser.add_argument("--seed", type=int, default=0)


def _finish(report: TestReport) -> None:
    print(json.dumps(report.summary()))
    for f in report.failures[:20]:
        print(f"FAIL: {f}", file=sys.stderr)
    sys.exit(0 if report.ok else 1)


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description="contract-based microservice tester")
    _common_args(parser)
    parser.add_argument(
        "--endpoint", default="predict", help="microservice endpoint (predict, ...)"
    )
    args = parser.parse_args(argv)
    tester = MicroserviceTester(
        Contract.load(args.contract), args.host, args.port,
        tensor=args.tensor, grpc=args.grpc, endpoint=args.endpoint,
        seed=args.seed, show=args.prnt,
    )
    _finish(asyncio.run(tester.run(args.n_requests, args.batch_size)))


def main_api(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description="deployed-API tester (gateway)")
    _common_args(parser)
    parser.add_argument("--oauth-key", required=True)
    parser.add_argument("--oauth-secret", required=True)
    parser.add_argument("--grpc-port", type=int, default=None)
    parser.add_argument(
        "--endpoint", default="predictions", choices=["predictions", "feedback"]
    )
    args = parser.parse_args(argv)
    tester = ApiTester(
        Contract.load(args.contract), args.host, args.port,
        args.oauth_key, args.oauth_secret,
        tensor=args.tensor, grpc=args.grpc, grpc_port=args.grpc_port,
        endpoint=args.endpoint, seed=args.seed, show=args.prnt,
    )
    _finish(asyncio.run(tester.run(args.n_requests, args.batch_size)))


if __name__ == "__main__":
    main()
