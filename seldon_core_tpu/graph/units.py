"""Component contract and built-in graph units.

A *component* is the user-supplied (or built-in) object behind a graph node.
The contract is duck-typed exactly like the reference wrapper runtime
(reference: wrappers/python/model_microservice.py:23-33,
router_microservice.py:18-22, transformer_microservice.py:15-38):

    predict(X, feature_names) -> ndarray          MODEL
    route(X, feature_names) -> int                ROUTER
    aggregate(Xs, features_list) -> ndarray       COMBINER
    transform_input(X, feature_names) -> ndarray  TRANSFORMER
    transform_output(X, feature_names) -> ndarray OUTPUT_TRANSFORMER
    send_feedback(X, feature_names, reward, truth, routing)  optional
    class_names: list[str]                        optional

Any method may be ``async def``.  Components may also implement the ``*_raw``
variants taking/returning :class:`Payload` for full control of meta/encoding.

Built-ins double as test fixtures and benchmark stubs, the reference's own
pattern (engine/.../predictors/SimpleModelUnit.java:33-46 et al.).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from seldon_core_tpu.graph.spec import Implementation


class GraphUnitError(Exception):
    """A unit rejected its input (maps to Status FAILURE on the wire)."""


class SeldonComponent:
    """Optional convenience base class; duck typing is what matters."""

    def init_metadata(self) -> dict[str, Any]:
        return {}

    def tags(self) -> dict[str, Any]:
        return {}

    def metrics(self) -> list[dict[str, Any]]:
        return []


# ---------------------------------------------------------------------------
# Built-in units
# ---------------------------------------------------------------------------

class SimpleModel(SeldonComponent):
    """Stub model returning a constant 3-class score row per input row —
    the reference's benchmark/default model
    (reference: engine/.../predictors/SimpleModelUnit.java:33-46)."""

    INLINE_SYNC = True  # microseconds of python math; skip the executor hop
    # DETERMINISTIC marks a component whose output is a pure function of
    # its input — the caching plane (docs/CACHING.md) only ever serves a
    # MODEL node from the response cache when the component declares it.
    # Stateful (Mahalanobis), randomized (RandomABTest), and feedback-
    # driven (bandit routers) components must NOT carry the mark.
    DETERMINISTIC = True

    values = np.array([0.1, 0.9, 0.5])
    class_names = ["class0", "class1", "class2"]

    def predict(self, X: np.ndarray, names: list[str]) -> np.ndarray:
        rows = X.shape[0] if getattr(X, "ndim", 0) >= 2 else 1
        return np.tile(self.values, (rows, 1))


class SimpleRouter(SeldonComponent):
    """Always routes to child 0
    (reference: engine/.../predictors/SimpleRouterUnit.java:28-31)."""

    INLINE_SYNC = True  # microseconds of python math; skip the executor hop
    DETERMINISTIC = True  # always child 0

    def route(self, X: np.ndarray, names: list[str]) -> int:
        return 0


class RandomABTest(SeldonComponent):
    """Routes to child 0 with probability ``ratioA``, else child 1; seeded for
    reproducibility (reference: engine/.../predictors/RandomABTestUnit.java:33-57,
    seeded Random(1337))."""

    INLINE_SYNC = True  # microseconds of python math; skip the executor hop

    def __init__(self, ratioA: float = 0.5, seed: int = 1337, **_: Any):
        self.ratio_a = float(ratioA)
        self._rng = np.random.default_rng(seed)

    def route(self, X: np.ndarray, names: list[str]) -> int:
        return 0 if self._rng.random() < self.ratio_a else 1


class AverageCombiner(SeldonComponent):
    """Element-wise mean of children outputs with strict shape agreement
    (reference: engine/.../predictors/AverageCombinerUnit.java:34-81).

    NOT inline-sync: the stack+mean copies scale with arbitrary child
    payload sizes — milliseconds of numpy on big batches belongs on the
    thread pool, not the event loop."""

    DETERMINISTIC = True  # pure element-wise mean

    def aggregate(self, Xs: list[np.ndarray], features: list[list[str]]) -> np.ndarray:
        if not Xs:
            raise GraphUnitError("AverageCombiner needs at least one input")
        arrs = [np.asarray(x, dtype=np.float64) for x in Xs]
        shape = arrs[0].shape
        for i, a in enumerate(arrs[1:], start=1):
            if a.shape != shape:
                raise GraphUnitError(
                    f"AverageCombiner shape mismatch: input 0 {shape} vs input {i} {a.shape}"
                )
        return np.mean(np.stack(arrs), axis=0)


class EpsilonGreedy(SeldonComponent):
    """Multi-armed-bandit router: explore with probability epsilon, otherwise
    exploit the best-performing branch; rewards arrive via the feedback loop
    (reference behaviour: examples/routers/epsilon_greedy/EpsilonGreedy.py:12-60)."""

    INLINE_SYNC = True  # microseconds of python math; skip the executor hop

    def __init__(
        self,
        n_branches: int = 2,
        epsilon: float = 0.1,
        verbose: bool = False,
        seed: int | None = 1337,
        **_: Any,
    ):
        if n_branches < 1:
            raise GraphUnitError("n_branches must be >= 1")
        self.n_branches = int(n_branches)
        self.epsilon = float(epsilon)
        self.verbose = bool(verbose)
        self._rng = np.random.default_rng(seed)
        self.pulls = np.zeros(self.n_branches, dtype=np.int64)
        self.value = np.zeros(self.n_branches, dtype=np.float64)

    def route(self, X: np.ndarray, names: list[str]) -> int:
        if self._rng.random() < self.epsilon:
            return int(self._rng.integers(self.n_branches))
        return int(np.argmax(self.value))

    def send_feedback(
        self,
        X: np.ndarray,
        names: list[str],
        reward: float,
        truth: Any = None,
        routing: int | None = None,
    ) -> None:
        if routing is None or not (0 <= routing < self.n_branches):
            return
        self.pulls[routing] += 1
        n = self.pulls[routing]
        # incremental mean of observed rewards per branch
        self.value[routing] += (reward - self.value[routing]) / n


class ThompsonSampling(SeldonComponent):
    """Beta-Bernoulli Thompson-sampling router (TPU-native extra beyond the
    reference's bandit example): sample a win-rate per branch, route argmax."""

    INLINE_SYNC = True  # microseconds of python math; skip the executor hop

    def __init__(self, n_branches: int = 2, seed: int | None = 1337, **_: Any):
        self.n_branches = int(n_branches)
        self._rng = np.random.default_rng(seed)
        self.alpha = np.ones(self.n_branches)
        self.beta = np.ones(self.n_branches)

    def route(self, X: np.ndarray, names: list[str]) -> int:
        samples = self._rng.beta(self.alpha, self.beta)
        return int(np.argmax(samples))

    def send_feedback(self, X, names, reward, truth=None, routing=None) -> None:
        if routing is None or not (0 <= routing < self.n_branches):
            return
        if reward > 0:
            self.alpha[routing] += reward
        else:
            self.beta[routing] += 1.0


class MahalanobisOutlier(SeldonComponent):
    """Online Mahalanobis-distance outlier scorer: incremental mean/covariance
    over the request stream, score = squared Mahalanobis distance of each row;
    annotates ``meta.tags.outlier_score`` as a TRANSFORMER
    (reference behaviour: examples/transformers/outlier_mahalanobis/
    OutlierMahalanobis.py:6-80 and wrappers/python/
    outlier_detector_microservice.py:23-56)."""

    def __init__(self, n_components: int = 0, n_stdev: float = 3.0, **_: Any):
        self.n_components = int(n_components)
        self.n_stdev = float(n_stdev)
        self.count = 0
        self._mean: np.ndarray | None = None
        self._m2: np.ndarray | None = None  # sum of outer-product deviations
        self._last_scores: np.ndarray | None = None

    def score(self, X: np.ndarray) -> np.ndarray:
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        d = X.shape[1]
        if self._mean is None:
            self._mean = np.zeros(d)
            self._m2 = np.zeros((d, d))
        scores = np.zeros(X.shape[0])
        for i, row in enumerate(X):
            if self.count >= 2:
                cov = self._m2 / (self.count - 1)
                cov = cov + 1e-6 * np.eye(d)  # ridge for invertibility
                delta = row - self._mean
                scores[i] = float(delta @ np.linalg.solve(cov, delta))
            # Welford update
            self.count += 1
            delta = row - self._mean
            self._mean += delta / self.count
            self._m2 += np.outer(delta, row - self._mean)
        self._last_scores = scores
        return scores

    def transform_input(self, X: np.ndarray, names: list[str]) -> np.ndarray:
        self.score(X)
        return X

    def tags(self) -> dict[str, Any]:
        if self._last_scores is None:
            return {}
        return {"outlier_score": self._last_scores.tolist()}


# ---------------------------------------------------------------------------
# Implementation registry
# ---------------------------------------------------------------------------

_BUILTINS: dict[Implementation, Callable[..., Any]] = {
    Implementation.SIMPLE_MODEL: SimpleModel,
    Implementation.SIMPLE_ROUTER: SimpleRouter,
    Implementation.RANDOM_ABTEST: RandomABTest,
    Implementation.AVERAGE_COMBINER: AverageCombiner,
    Implementation.EPSILON_GREEDY: EpsilonGreedy,
    Implementation.THOMPSON_SAMPLING: ThompsonSampling,
    Implementation.MAHALANOBIS_OUTLIER: MahalanobisOutlier,
    Implementation.JAX_MODEL: lambda **p: _jax_model(p),
    Implementation.JAX_GENERATIVE: lambda **p: _jax_generative(p),
    # LLM graph plane (docs/GRAPHS.md) — lazy imports: the graphllm
    # package pulls runtime settings the plain graph path never needs
    Implementation.CASCADE_ROUTER: lambda **p: _graphllm("CascadeRouter", p),
    Implementation.GUARDRAIL: lambda **p: _graphllm("Guardrail", p),
}


def _graphllm(cls_name: str, parameters: dict[str, Any]) -> Any:
    import seldon_core_tpu.graphllm as graphllm

    return getattr(graphllm, cls_name)(**parameters)


def _parse_dtype(raw: Any, impl_name: str) -> Any:
    """Map a graph-parameter dtype string to a JAX dtype (None = keep)."""
    import jax.numpy as jnp

    dtypes = {"bfloat16": jnp.bfloat16, "float16": jnp.float16, "float32": None, None: None}
    if raw not in dtypes:
        raise GraphUnitError(
            f"{impl_name} dtype must be one of "
            f"{sorted(k for k in dtypes if k)}, got {raw!r}"
        )
    return dtypes[raw]


def _parse_mesh(raw: Any, impl_name: str):
    """Graph-parameter mesh request -> jax.sharding.Mesh.

    ``"auto"`` picks a serving mesh over every visible device (all hosts of
    the slice — the mesh spans processes on multi-host, and CompiledModel
    coordinates steps through the MultihostDriver); ``"tp=4,fsdp=2"`` etc.
    names an explicit MeshPlan factorization.
    """
    if raw is None:
        return None
    from seldon_core_tpu.parallel import MeshPlan, best_mesh, make_mesh

    raw = str(raw).strip()
    if raw in ("auto", "all"):
        return best_mesh()
    try:
        axes = {}
        for part in raw.split(","):
            k, _, v = part.partition("=")
            axes[k.strip()] = int(v)
        return make_mesh(MeshPlan(**axes))
    except (ValueError, TypeError) as e:
        raise GraphUnitError(
            f"{impl_name} mesh must be 'auto' or 'dp=..,fsdp=..,tp=..,sp=..', "
            f"got {raw!r}: {e}"
        ) from None


def _jax_model(parameters: dict[str, Any]) -> Any:
    """JAX_MODEL implementation: compile a model-zoo family on device.

    Graph parameters: ``family`` (required), ``preset``, ``dtype``
    ("bfloat16"/"float16"/"float32"), ``max_batch``, ``max_delay_ms``,
    ``buckets`` (comma-separated batch ladder, e.g. "8,32" — big models
    want few compiled programs), ``mesh`` ("auto" or "tp=4,fsdp=2" — shards
    params over the slice per the family's logical axes), ``input_dtype``
    (warm the buckets for a non-default wire dtype, e.g. "uint8" images
    normalized on device), plus any model-config field override (e.g.
    ``n_classes``).
    """
    from seldon_core_tpu.models import registry as model_registry

    params = dict(parameters)
    try:
        family = params.pop("family")
    except KeyError:
        raise GraphUnitError("JAX_MODEL requires a 'family' parameter") from None
    dtype = _parse_dtype(params.pop("dtype", None), "JAX_MODEL")
    mesh = _parse_mesh(params.pop("mesh", None), "JAX_MODEL")
    if mesh is not None:
        params["mesh"] = mesh
    sharding = str(params.pop("sharding", "default")).strip()
    if sharding == "fsdp":
        from seldon_core_tpu.parallel.sharding import FSDP_RULES

        params["rules"] = FSDP_RULES
    elif sharding != "default":
        raise GraphUnitError(
            f"JAX_MODEL sharding must be 'default' or 'fsdp', got {sharding!r}"
        )
    raw_buckets = params.pop("buckets", None)
    if raw_buckets is not None:
        from seldon_core_tpu.executor import BucketSpec

        try:
            sizes = tuple(sorted(int(s) for s in str(raw_buckets).split(",")))
            if not sizes or any(s < 1 for s in sizes):
                raise ValueError(sizes)
        except ValueError:
            raise GraphUnitError(
                f"buckets must be comma-separated positive ints, got {raw_buckets!r}"
            ) from None
        params["buckets"] = BucketSpec(sizes)
    try:
        return model_registry.build_component(family, dtype=dtype, **params)
    except (KeyError, TypeError) as e:
        raise GraphUnitError(str(e)) from e


def _jax_generative(parameters: dict[str, Any]) -> Any:
    """JAX_GENERATIVE implementation: continuous-batching token generation.

    Graph parameters: ``family`` (default "llama"), ``preset``, ``n_slots``,
    ``max_new_tokens``, ``temperature``, ``top_k`` (fused on-device top-k
    sampling), ``eos_id``, ``dtype``, ``checkpoint``, ``seq_impl``,
    ``decode_block``, ``overlap`` (overlapped decode pipeline,
    docs/PERFORMANCE.md), ``kv_prefix_reuse``, ``prefix_dram_gb``
    (host-DRAM prefix tier, docs/CACHING.md), ``spec_draft`` /
    ``spec_ngram`` / ``spec_hist`` (fused self-speculative decoding) with
    ``spec_method`` / ``spec_heads`` / ``spec_heads_path`` /
    ``spec_draft_model`` (learned proposers: fused Medusa-style heads or a
    co-resident draft model, docs/PERFORMANCE.md §6),
    ``kv_cache_dtype`` (``int8`` paged-KV quantization), ``prefill_chunk``
    (Sarathi-style chunked prefill interleaved with decode),
    ``decode_kernel`` (fused Pallas paged decode-attention kernel),
    ``lora_rank`` / ``lora_slots`` / ``lora_targets`` / ``lora_adapters``
    / ``adapter`` (batched multi-LoRA serving, docs/MULTITENANT.md),
    ``pack_class`` / ``pack_slo_ms`` (chip packing: this deployment's QoS
    class and queue-wait SLO band on a time-shared device,
    docs/PACKING.md), ``conf_signal`` (compile the cascade confidence
    signal into the fused decode programs) and ``embed`` (warm the
    pooled-embedding programs for the /embeddings route — docs/GRAPHS.md),
    plus model-config overrides.
    """
    from seldon_core_tpu.models import registry as model_registry

    params = dict(parameters)
    family = params.pop("family", "llama")
    dtype = _parse_dtype(params.pop("dtype", None), "JAX_GENERATIVE")
    mesh = _parse_mesh(params.pop("mesh", None), "JAX_GENERATIVE")
    if mesh is not None:
        params["mesh"] = mesh
    try:
        return model_registry.build_generative_component(
            family, dtype=dtype, **params
        )
    except (KeyError, TypeError) as e:
        raise GraphUnitError(str(e)) from e


def create_builtin(impl: Implementation, parameters: dict[str, Any]) -> Any:
    """Instantiate a built-in implementation with its typed parameters
    (reference analogue: PredictorConfigBean's implementation->bean map)."""
    try:
        factory = _BUILTINS[impl]
    except KeyError:
        raise GraphUnitError(f"no built-in implementation {impl!r}") from None
    return factory(**parameters)


def has_builtin(impl: Implementation) -> bool:
    return impl in _BUILTINS
