"""Desired-state generation: SeldonDeployment -> k8s Deployments + Services.

The reference emits, per predictor: one engine Deployment (graph spec passed
base64 in ``ENGINE_PREDICTOR``, prometheus scrape annotations, readiness on
the admin port, preStop pause+drain), one Deployment per componentSpec, one
ClusterIP Service per distinct graph container, and one deployment-wide
Service pointing at the engine (reference:
SeldonDeploymentOperatorImpl.java:520-666, :98-144 engine container,
:195-292 container update, :465-484 ambassador annotations).

All objects carry the ``seldon-deployment-id`` label the controller uses for
ownership and orphan GC.
"""

from __future__ import annotations

import base64
import copy
import json
from typing import Any

from seldon_core_tpu.operator.crd import (
    LABEL_DEPLOYMENT_ID,
    LABEL_SELDON_TYPE,
    PredictorDef,
    SeldonDeployment,
)
from seldon_core_tpu.operator.names import (
    component_deployment_name,
    deployment_service_name,
    engine_deployment_name,
    mesh_service_name,
    service_name,
)
from seldon_core_tpu.operator.tpu import TpuSpec

from seldon_core_tpu import __version__ as _VERSION

ENGINE_IMAGE_DEFAULT = f"seldon-core-tpu/engine:{_VERSION}"
ENGINE_REST_PORT = 8000
ENGINE_GRPC_PORT = 5001

# Disaggregated prefill/decode (docs/DISAGGREGATION.md): a predictor (or
# CR-wide) annotation sets the engine's pool role and — for prefill pools —
# the decode peers its KV handoffs stream to; the operator turns them into
# the engine's SCT_ENGINE_ROLE / SCT_DISAGG_DECODE env.
ENGINE_ROLE_ANNOTATION = "seldon.io/engine-role"
DISAGG_DECODE_ANNOTATION = "seldon.io/disagg-decode"
ENGINE_ROLES = ("prefill", "decode", "unified")
# health/drain/metrics are served on the REST port (the reference used a
# second Tomcat "admin" connector on 8082; this engine has one listener)
ENGINE_ADMIN_PORT = ENGINE_REST_PORT

# Multi-host mesh boot contract: one pod per TPU host; the coordinator is
# the slice's ordinal-0 pod, reachable by stable DNS through the headless
# mesh Service.  Shared (jax-free) source: utils/mesh_contract.py.
from seldon_core_tpu.utils.mesh_contract import (  # noqa: E402
    DEFAULT_COORDINATOR_PORT as COORDINATOR_PORT,
    ENV_COORDINATOR_PORT,
    ENV_MESH_SERVICE,
    ENV_NUM_PROCESSES,
    ENV_POD_NAME,
)


def engine_container(mldep: SeldonDeployment, predictor: PredictorDef, image: str) -> dict[str, Any]:
    # replicas excluded: the engine doesn't use it at runtime, and baking it
    # into the pod env would turn a scale-only change into a template change
    # (which rolls every pod of a multi-host slice)
    predictor_json = json.dumps(
        predictor.model_dump(exclude={"componentSpecs", "replicas"}), sort_keys=True
    )
    container = {
        "name": "seldon-container-engine",
        "image": image,
        "env": [
            {
                "name": "ENGINE_PREDICTOR",
                "value": base64.b64encode(predictor_json.encode()).decode(),
            },
            {"name": "SELDON_DEPLOYMENT_ID", "value": mldep.metadata.name},
            {"name": "ENGINE_SERVER_PORT", "value": str(ENGINE_REST_PORT)},
            {"name": "ENGINE_SERVER_GRPC_PORT", "value": str(ENGINE_GRPC_PORT)},
        ],
        "ports": [
            {"containerPort": ENGINE_REST_PORT, "name": "rest", "protocol": "TCP"},
            {"containerPort": ENGINE_GRPC_PORT, "name": "grpc", "protocol": "TCP"},
        ],
        "readinessProbe": {
            "httpGet": {"path": "/ready", "port": ENGINE_ADMIN_PORT},
            "initialDelaySeconds": 10,
            "periodSeconds": 5,
            "failureThreshold": 3,
        },
        # startupProbe holds liveness off while the engine blocks in
        # jax.distributed.initialize (multi-host mesh formation can wait
        # minutes for node-pool autoscaling) or in first-boot XLA warmup;
        # without it the kubelet kills the pod after ~25s of unreachable
        # /ping and a staggered CrashLoopBackOff can keep the mesh from
        # ever forming
        "startupProbe": {
            "httpGet": {"path": "/ping", "port": ENGINE_ADMIN_PORT},
            "periodSeconds": 10,
            "failureThreshold": 90,
        },
        "livenessProbe": {
            "httpGet": {"path": "/ping", "port": ENGINE_ADMIN_PORT},
            "initialDelaySeconds": 10,
            "periodSeconds": 5,
        },
        "lifecycle": {
            "preStop": {
                "exec": {
                    "command": [
                        "/bin/sh",
                        "-c",
                        f"curl -s -X POST localhost:{ENGINE_ADMIN_PORT}/pause && sleep 5",
                    ]
                }
            }
        },
        # deep-copied: apply_to_container mutates, and aliasing the CR's
        # engineResources dict would leak TPU limits into the spec writeback
        # (changing ENGINE_PREDICTOR between operator runs -> spurious rolls)
        "resources": copy.deepcopy(predictor.engineResources)
        or {"requests": {"cpu": "0.1"}},
    }
    # disagg role injection: predictor annotation wins, CR-wide annotation
    # is the pool default; absent -> unified (the engine's own default, no
    # env emitted so a scale-only change stays template-stable)
    role = (
        predictor.annotations.get(ENGINE_ROLE_ANNOTATION)
        or mldep.metadata.annotations.get(ENGINE_ROLE_ANNOTATION)
        or ""
    ).strip().lower()
    if role:
        container["env"].append({"name": "SCT_ENGINE_ROLE", "value": role})
    peers = (
        predictor.annotations.get(DISAGG_DECODE_ANNOTATION)
        or mldep.metadata.annotations.get(DISAGG_DECODE_ANNOTATION)
        or ""
    ).strip()
    if peers:
        container["env"].append({"name": "SCT_DISAGG_DECODE", "value": peers})
    if predictor.tpu is not None:
        # the engine pod hosts the LOCAL JAX units, so it is the TPU
        # consumer: device-plugin resource on the container (defaulting.py
        # sets predictor.tpu whenever the graph holds JAX units)
        predictor.tpu.apply_to_container(container)
        if predictor.tpu.hosts > 1:
            container["env"].extend(
                [
                    {"name": ENV_NUM_PROCESSES, "value": str(predictor.tpu.hosts)},
                    {
                        "name": ENV_MESH_SERVICE,
                        "value": mesh_service_name(mldep.metadata.name, predictor.name),
                    },
                    {"name": ENV_COORDINATOR_PORT, "value": str(COORDINATOR_PORT)},
                    {
                        "name": ENV_POD_NAME,
                        "valueFrom": {"fieldRef": {"fieldPath": "metadata.name"}},
                    },
                ]
            )
            container["ports"].append(
                {
                    "containerPort": COORDINATOR_PORT,
                    "name": "coordinator",
                    "protocol": "TCP",
                }
            )
    return container


def _labels(mldep: SeldonDeployment, extra: dict[str, str] | None = None) -> dict[str, str]:
    labels = {LABEL_DEPLOYMENT_ID: mldep.metadata.name, "app": "seldon"}
    if extra:
        labels.update(extra)
    return labels


def _deployment(
    name: str,
    namespace: str,
    labels: dict[str, str],
    pod_labels: dict[str, str],
    pod_spec: dict[str, Any],
    replicas: int,
    annotations: dict[str, str] | None = None,
) -> dict[str, Any]:
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": dict(labels),
        },
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app.kubernetes.io/name": name}},
            "strategy": {
                "rollingUpdate": {"maxUnavailable": "10%"},
                "type": "RollingUpdate",
            },
            "template": {
                "metadata": {
                    "labels": {**pod_labels, "app.kubernetes.io/name": name},
                    "annotations": annotations or {},
                },
                "spec": pod_spec,
            },
        },
    }


def _statefulset(
    name: str,
    namespace: str,
    labels: dict[str, str],
    pod_labels: dict[str, str],
    pod_spec: dict[str, Any],
    replicas: int,
    service_name: str,
    annotations: dict[str, str] | None = None,
) -> dict[str, Any]:
    """Multi-host engine slices are StatefulSets: stable pod ordinals give
    each TPU host its JAX process id, and the headless Service gives the
    ordinal-0 coordinator a stable DNS name (parallel/distributed.py)."""
    return {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": dict(labels),
        },
        "spec": {
            "replicas": replicas,
            "serviceName": service_name,
            "podManagementPolicy": "Parallel",  # all hosts must boot to form the mesh
            # RollingUpdate would wedge: worker pods never report Ready (by
            # design — see engine/app.py mesh_worker), and a slice's XLA
            # programs must match across hosts anyway, so updates are
            # whole-slice restarts: the controller deletes the slice's pods
            # after pushing a changed spec (Controller._roll_statefulset)
            "updateStrategy": {"type": "OnDelete"},
            "selector": {"matchLabels": {"app.kubernetes.io/name": name}},
            "template": {
                "metadata": {
                    "labels": {**pod_labels, "app.kubernetes.io/name": name},
                    "annotations": annotations or {},
                },
                "spec": pod_spec,
            },
        },
    }


def create_resources(
    mldep: SeldonDeployment, engine_image: str = ENGINE_IMAGE_DEFAULT
) -> tuple[list[dict[str, Any]], list[dict[str, Any]]]:
    """-> (workloads, services) — the full desired state for one CR.
    Workloads are Deployments, plus StatefulSets for multi-host slices."""
    ns = mldep.metadata.namespace
    deployments: list[dict[str, Any]] = []
    services: list[dict[str, Any]] = []

    for predictor in mldep.spec.predictors:
        # engine deployment (the per-predictor orchestrator pod)
        eng_name = engine_deployment_name(mldep.metadata.name, predictor.name)
        eng_labels = _labels(mldep, {LABEL_SELDON_TYPE: "engine"})
        eng_pod_labels = {
            **_labels(mldep),
            "seldon-app": deployment_service_name(mldep.metadata.name),
        }
        eng_pod_spec = {
            "containers": [engine_container(mldep, predictor, engine_image)],
            "terminationGracePeriodSeconds": 20,
        }
        eng_annotations = {
            "prometheus.io/scrape": "true",
            "prometheus.io/path": "/prometheus",
            "prometheus.io/port": str(ENGINE_ADMIN_PORT),
        }
        if predictor.tpu is not None:
            predictor.tpu.apply_to_pod(eng_pod_spec)
        if predictor.tpu is not None and predictor.tpu.hosts > 1:
            # one pod per TPU host; ordinal // hosts = slice replica group,
            # ordinal % hosts = JAX process id within the slice.  Ingress
            # readiness is only reported by process 0 of each slice (the
            # engine boot contract), so the deployment-wide Service routes
            # to coordinators only.
            mesh_svc = mesh_service_name(mldep.metadata.name, predictor.name)
            deployments.append(
                _statefulset(
                    eng_name,
                    ns,
                    eng_labels,
                    eng_pod_labels,
                    eng_pod_spec,
                    predictor.replicas * predictor.tpu.hosts,
                    mesh_svc,
                    annotations=eng_annotations,
                )
            )
            services.append(
                {
                    "apiVersion": "v1",
                    "kind": "Service",
                    "metadata": {
                        "name": mesh_svc,
                        "namespace": ns,
                        "labels": _labels(mldep),
                    },
                    "spec": {
                        "clusterIP": "None",  # headless: per-pod DNS records
                        "publishNotReadyAddresses": True,  # pods need DNS before the mesh is up
                        "selector": {"app.kubernetes.io/name": eng_name},
                        "ports": [
                            {
                                "port": COORDINATOR_PORT,
                                "targetPort": COORDINATOR_PORT,
                                "name": "coordinator",
                            }
                        ],
                    },
                }
            )
        else:
            deployments.append(
                _deployment(
                    eng_name,
                    ns,
                    eng_labels,
                    eng_pod_labels,
                    eng_pod_spec,
                    predictor.replicas,
                    annotations=eng_annotations,
                )
            )

        # component deployments (user model pods)
        for idx, cspec in enumerate(predictor.componentSpecs):
            cname = component_deployment_name(mldep.metadata.name, predictor.name, idx)
            pod_spec = cspec.get("spec", {})
            metadata = cspec.get("metadata", {})
            pod_labels = {
                **_labels(mldep),
                **metadata.get("labels", {}),
                # selector value is the (deployment,predictor,container)-unique
                # service name: a container called "classifier" in another
                # SeldonDeployment must not match this Service
                **{
                    f"seldon-app-svc-{c.get('name', '')}": service_name(
                        mldep.metadata.name, predictor.name, c.get("name", "")
                    )
                    for c in pod_spec.get("containers", [])
                },
            }
            deployments.append(
                _deployment(
                    cname,
                    ns,
                    _labels(mldep, {LABEL_SELDON_TYPE: "deployment"}),
                    pod_labels,
                    pod_spec,
                    predictor.replicas,
                    annotations=metadata.get("annotations", {}),
                )
            )
            # one ClusterIP service per distinct container
            for c in pod_spec.get("containers", []):
                container_name = c.get("name", "")
                port = None
                for e in c.get("env", []):
                    if e.get("name") == "PREDICTIVE_UNIT_SERVICE_PORT":
                        port = int(e["value"])
                if port is None:
                    continue
                svc = service_name(mldep.metadata.name, predictor.name, container_name)
                services.append(
                    {
                        "apiVersion": "v1",
                        "kind": "Service",
                        "metadata": {
                            "name": svc,
                            "namespace": ns,
                            "labels": _labels(mldep),
                        },
                        "spec": {
                            "type": "ClusterIP",
                            "selector": {f"seldon-app-svc-{container_name}": svc},
                            "ports": [
                                {"port": port, "targetPort": port, "protocol": "TCP"}
                            ],
                        },
                    }
                )

    # deployment-wide service -> engine pods (what the gateway resolves by
    # name; carries the ambassador routing annotations like the reference)
    dep_svc = deployment_service_name(mldep.metadata.name)
    services.append(
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": dep_svc,
                "namespace": ns,
                "labels": _labels(mldep),
                "annotations": {
                    "getambassador.io/config": json.dumps(
                        {
                            "apiVersion": "ambassador/v0",
                            "kind": "Mapping",
                            "name": f"seldon_{mldep.metadata.name}_rest_mapping",
                            "prefix": f"/seldon/{mldep.metadata.name}/",
                            "service": f"{dep_svc}:{ENGINE_REST_PORT}",
                        }
                    )
                },
            },
            "spec": {
                "type": "ClusterIP",
                "selector": {"seldon-app": dep_svc},
                "ports": [
                    {"port": ENGINE_REST_PORT, "targetPort": ENGINE_REST_PORT, "name": "rest"},
                    {"port": ENGINE_GRPC_PORT, "targetPort": ENGINE_GRPC_PORT, "name": "grpc"},
                ],
            },
        }
    )
    return deployments, services
