"""Hot-path perf gates (docs/PERFORMANCE.md), CPU-safe for CI:

* host-sync audit — steady-state decode must pay ZERO per-token host
  syncs (one fetch per fused k-token block, the overlapped pipeline's
  contract), counted by the PR-3 always-on probe;
* warmup plane — /stats/warmup attributes the readiness tail per unit,
  and a warmed stub engine's p99 stays bounded relative to its p95
  (first-touch compiles must never land on a user request);
* overlap smoke — the overlap actually engages under concurrent load.

``make perf-check`` runs exactly this file.
"""

from __future__ import annotations

import asyncio
import json

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from seldon_core_tpu.engine.app import EngineApp
from seldon_core_tpu.engine.service import PredictionService
from seldon_core_tpu.executor.generation import (
    GenerationScheduler,
    GenerativeModel,
)
from seldon_core_tpu.graph.spec import PredictorSpec
from seldon_core_tpu.models import llama

run = asyncio.run


@pytest.fixture(scope="module")
def tiny():
    import jax

    cfg = llama.Config.tiny(max_seq=64)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestHostSyncAudit:
    """The PR-3 host-sync counter audits the decode loop: syncs per
    generated token must be ~1/decode_block, never ~1."""

    def test_steady_state_decode_has_no_per_token_syncs(self, tiny):
        from seldon_core_tpu.obs import host_sync_snapshot

        cfg, params = tiny
        block = 8
        max_new = 24
        n_req = 3
        model = GenerativeModel(
            cfg, params, n_slots=4, decode_block=block, name="sync-audit"
        )
        sched = GenerationScheduler(model, overlap=True)
        before = host_sync_snapshot().get("sync-audit", 0)

        async def go():
            try:
                return await asyncio.gather(
                    *(
                        sched.submit(
                            np.asarray([5 + i, 9, 2], np.int32),
                            max_new_tokens=max_new,
                        )
                        for i in range(n_req)
                    )
                )
            finally:
                await sched.close()

        outs = run(go())
        assert all(o.size == max_new for o in outs)
        syncs = host_sync_snapshot().get("sync-audit", 0) - before
        tokens = n_req * max_new
        # one fetch per fused block (+ slack for the final speculative
        # block and ragged admission rounds) — NOT one per token
        budget = tokens // block + 4
        assert syncs <= budget, f"{syncs} host syncs for {tokens} tokens"
        assert syncs < tokens / 2, "per-token sync pattern detected"
        # the overlap engaged: blocks were dispatched from the device carry
        assert model.overlapped >= 1


class TestWarmupPlane:
    JAX_PREDICTOR = {
        "name": "warm",
        "graph": {
            "name": "m",
            "type": "MODEL",
            "implementation": "JAX_MODEL",
            "parameters": [
                {"name": "family", "value": "mlp", "type": "STRING"},
                {"name": "preset", "value": "tiny", "type": "STRING"},
            ],
        },
    }

    def test_stats_warmup_attributes_the_readiness_tail(self):
        """GET /stats/warmup reports per-unit programs + seconds once
        readiness flips — the attribution for a slow warm start."""

        async def go():
            service = PredictionService(
                PredictorSpec.model_validate(self.JAX_PREDICTOR)
            )
            app = EngineApp(service).build()
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                deadline = asyncio.get_event_loop().time() + 120
                while asyncio.get_event_loop().time() < deadline:
                    if (await client.get("/ready")).status == 200:
                        break
                    await asyncio.sleep(0.1)
                resp = await client.get("/stats/warmup")
                assert resp.status == 200
                snap = (await resp.json())["warmup"]
                assert snap["warmed"] is True
                assert snap["error"] is None
                model = service.walker.root.client.component.model
                assert snap["programs"]["m"] == len(model.buckets.sizes)
                assert snap["seconds"]["m"] > 0
                assert snap["total_seconds"] >= snap["seconds"]["m"] * 0.5
            finally:
                await client.close()

        run(go())

    def test_warm_start_p99_bound_on_stub_graph(self):
        """After readiness, a stub graph's tail must be queueing noise,
        not compile spikes: p99 bounded by max(2x p95, p95 + 25ms, 30ms)
        over a short in-process load burst (floors absorb shared-CI
        scheduler jitter; a first-touch compile is 100x the floor)."""
        import time

        async def go():
            service = PredictionService(
                PredictorSpec.model_validate(
                    {"name": "p", "graph": {"name": "m", "type": "MODEL",
                                            "implementation": "SIMPLE_MODEL"}}
                )
            )
            app = EngineApp(service).build()
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                assert (await client.get("/ready")).status == 200
                body = {"data": {"ndarray": [[1.0, 2.0, 3.0]]}}
                lat: list[float] = []

                async def one():
                    t0 = time.perf_counter()
                    resp = await client.post("/api/v0.1/predictions", json=body)
                    assert resp.status == 200
                    await resp.read()
                    lat.append(time.perf_counter() - t0)

                # small warm trickle, then the measured burst
                for _ in range(5):
                    await one()
                lat.clear()
                for _ in range(30):
                    await asyncio.gather(*(one() for _ in range(8)))
                lat.sort()
                p95 = lat[int(len(lat) * 0.95) - 1] * 1e3
                p99 = lat[int(len(lat) * 0.99) - 1] * 1e3
                bound = max(2 * p95, p95 + 25.0, 30.0)
                assert p99 <= bound, f"p99 {p99:.1f}ms > bound {bound:.1f}ms (p95 {p95:.1f}ms)"
            finally:
                await client.close()

        run(go())


class TestOverlapConfig:
    def test_env_kill_switch_disables_overlap(self, tiny, monkeypatch):
        cfg, params = tiny
        monkeypatch.setenv("SCT_GEN_OVERLAP", "0")
        model = GenerativeModel(cfg, params, n_slots=2, decode_block=4)
        sched = GenerationScheduler(model)
        assert sched.overlap is False

        async def go():
            try:
                return await sched.submit(
                    np.asarray([5, 9, 2], np.int32), max_new_tokens=8
                )
            finally:
                await sched.close()

        out = run(go())
        assert out.size == 8
        assert model.overlapped == 0

    def test_decode_block_one_never_overlaps(self, tiny):
        cfg, params = tiny
        model = GenerativeModel(cfg, params, n_slots=2, decode_block=1)
        sched = GenerationScheduler(model, overlap=True)
        assert sched.overlap is False
