"""Request-correlation id generation.

The reference tags every request with a 130-bit random ``puid`` carried in
``Meta`` and used as the Kafka message key (reference:
engine/.../service/PredictionService.java:52-58)."""

import secrets


def make_puid() -> str:
    """33 base-32-ish hex chars of cryptographic randomness (>=130 bits)."""
    return secrets.token_hex(17)
