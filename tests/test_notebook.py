"""The notebooks must actually run — the reference's notebooks were its
de-facto integration suite (SURVEY §4), so ours are executable too."""

import os

import nbformat
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NOTEBOOKS = [
    "serving_walkthrough.ipynb",
    "graphs_and_canary.ipynb",
    "operator_end_to_end.ipynb",
]


@pytest.mark.slow
@pytest.mark.parametrize("name", NOTEBOOKS)
def test_notebook_executes(name):
    path = os.path.join(REPO_ROOT, "notebooks", name)
    nb = nbformat.read(path, as_version=4)
    # execute the code cells in one namespace, like a kernel would
    ns: dict = {}
    try:
        for cell in nb.cells:
            if cell.cell_type == "code":
                exec(compile("".join(cell.source), path, "exec"), ns)  # noqa: S102
    finally:
        # a cell that raised may have left engine/gateway subprocesses
        # running — they would squat their ports for every later test
        import subprocess

        for v in list(ns.values()):
            if isinstance(v, subprocess.Popen) and v.poll() is None:
                v.terminate()
                try:
                    v.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    v.kill()
