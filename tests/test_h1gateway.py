"""h1 splice front end tests (gateway/h1gateway.py): the gateway's default
REST data plane.  Covers the raw splice hot path (auth, verbatim forward,
keep-alive, pipelined multiplexing), the fallback endpoints (oauth, ops,
feedback), framing strictness (content-length smuggling guards, chunked
uploads), chunked/SSE response forwarding, and engine-failure handling."""

import asyncio
import json

import aiohttp
import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from seldon_core_tpu.engine.app import EngineApp
from seldon_core_tpu.engine.service import PredictionService
from seldon_core_tpu.gateway.app import GatewayApp
from seldon_core_tpu.gateway.h1gateway import H1SpliceFrontend
from seldon_core_tpu.gateway.store import DeploymentRecord, DeploymentStore
from seldon_core_tpu.graph.spec import PredictorSpec

run = asyncio.run

SIMPLE = {"name": "p", "graph": {"name": "m", "type": "MODEL", "implementation": "SIMPLE_MODEL"}}


async def _engine_client(spec=SIMPLE) -> TestClient:
    service = PredictionService(PredictorSpec.model_validate(spec))
    await service.start()
    app = EngineApp(service).build()
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


async def _frontend(engine_port: int, **gw_kwargs):
    store = DeploymentStore()
    store.put(
        DeploymentRecord(
            name="dep",
            oauth_key="key1",
            oauth_secret="sec1",
            engine_host="127.0.0.1",
            engine_rest_port=engine_port,
        )
    )
    gw = GatewayApp(store, **gw_kwargs)
    frontend = H1SpliceFrontend(gw)
    port = await frontend.start(0, host="127.0.0.1")
    return frontend, gw, port


async def _token(session: aiohttp.ClientSession, port: int) -> str:
    resp = await session.post(
        f"http://127.0.0.1:{port}/oauth/token",
        data={"grant_type": "client_credentials", "client_id": "key1", "client_secret": "sec1"},
    )
    assert resp.status == 200
    return (await resp.json())["access_token"]


class TestSplicePredict:
    def test_predict_keepalive_and_ops(self):
        async def go():
            engine = await _engine_client()
            frontend, gw, port = await _frontend(engine.server.port)
            async with aiohttp.ClientSession() as s:
                tok = await _token(s, port)
                hdrs = {"Authorization": f"Bearer {tok}"}
                out = []
                # three spliced requests over ONE keep-alive connection
                for _ in range(3):
                    r = await s.post(
                        f"http://127.0.0.1:{port}/api/v0.1/predictions",
                        json={"data": {"ndarray": [[1.0, 2.0]]}},
                        headers=hdrs,
                    )
                    out.append((r.status, await r.json()))
                ping = await s.get(f"http://127.0.0.1:{port}/ping")
                ready = await s.get(f"http://127.0.0.1:{port}/ready")
                prom = await s.get(f"http://127.0.0.1:{port}/prometheus")
                prom_text = await prom.text()
                await frontend.stop()
                await engine.close()
                return out, ping.status, ready.status, prom.status, prom_text

        out, ping, ready, prom, prom_text = run(go())
        assert all(st == 200 for st, _ in out)
        assert out[0][1]["data"]["ndarray"] == [[0.1, 0.9, 0.5]]
        assert (ping, ready, prom) == (200, 200, 200)
        assert "ingress" in prom_text

    def test_auth_rejected_on_splice_path(self):
        async def go():
            engine = await _engine_client()
            frontend, gw, port = await _frontend(engine.server.port)
            async with aiohttp.ClientSession() as s:
                r1 = await s.post(
                    f"http://127.0.0.1:{port}/api/v0.1/predictions", json={}
                )
                b1 = await r1.json()
                r2 = await s.post(
                    f"http://127.0.0.1:{port}/api/v0.1/predictions",
                    json={},
                    headers={"Authorization": "Bearer junk"},
                )
                # connection stays usable after an auth failure
                tok = await _token(s, port)
                r3 = await s.post(
                    f"http://127.0.0.1:{port}/api/v0.1/predictions",
                    json={"data": {"ndarray": [[1.0]]}},
                    headers={"Authorization": f"Bearer {tok}"},
                )
                await frontend.stop()
                await engine.close()
                return r1.status, b1, r2.status, r3.status

        s1, b1, s2, s3 = run(go())
        assert s1 == 401 and b1["status"]["code"] == 401
        assert s2 == 401
        assert s3 == 200

    def test_concurrent_requests_multiplex(self):
        async def go():
            engine = await _engine_client()
            frontend, gw, port = await _frontend(engine.server.port)
            async with aiohttp.ClientSession() as s:
                tok = await _token(s, port)
                hdrs = {"Authorization": f"Bearer {tok}"}

                async def one(i):
                    r = await s.post(
                        f"http://127.0.0.1:{port}/api/v0.1/predictions",
                        json={"data": {"ndarray": [[float(i), 2.0]]}},
                        headers=hdrs,
                    )
                    return r.status, (await r.json())["status"]["code"]

                results = await asyncio.gather(*(one(i) for i in range(24)))
                # multiplexing respected the upstream conn cap
                pool = next(iter(frontend._pools.values()))
                n_conns = len(pool.conns)
                await frontend.stop()
                await engine.close()
                return results, n_conns

        results, n_conns = run(go())
        assert all(r == (200, 200) for r in results)
        from seldon_core_tpu.gateway.h1gateway import _MAX_UPSTREAM_CONNS

        assert 1 <= n_conns <= _MAX_UPSTREAM_CONNS

    def test_feedback_fallback_and_reward(self):
        async def go():
            engine = await _engine_client()
            frontend, gw, port = await _frontend(engine.server.port)
            async with aiohttp.ClientSession() as s:
                tok = await _token(s, port)
                r = await s.post(
                    f"http://127.0.0.1:{port}/api/v0.1/feedback",
                    json={"reward": 1.0},
                    headers={"Authorization": f"Bearer {tok}"},
                )
                status = r.status
                await frontend.stop()
                await engine.close()
                return status

        assert run(go()) == 200

    def test_engine_down_gives_503(self):
        async def go():
            frontend, gw, port = await _frontend(1)  # port 1: refused
            async with aiohttp.ClientSession() as s:
                tok = await _token(s, port)
                r = await s.post(
                    f"http://127.0.0.1:{port}/api/v0.1/predictions",
                    json={"data": {"ndarray": [[1.0]]}},
                    headers={"Authorization": f"Bearer {tok}"},
                )
                body = await r.json()
                await frontend.stop()
                return r.status, body

        status, body = run(go())
        assert status == 503
        assert body["status"]["code"] == 503

    def test_404_unknown_route(self):
        async def go():
            frontend, gw, port = await _frontend(1)
            async with aiohttp.ClientSession() as s:
                r = await s.get(f"http://127.0.0.1:{port}/nope")
                await frontend.stop()
                return r.status

        assert run(go()) == 404


class TestFramingStrictness:
    """The splice forwards raw bytes onto a SHARED pipelined engine
    connection — framing the gateway and engine could read differently is
    a smuggling vector and must be rejected."""

    async def _raw(self, port: int, payload: bytes) -> bytes:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(payload)
        await writer.drain()
        data = await reader.read(4096)
        writer.close()
        return data

    def test_bad_content_length_rejected(self):
        async def go():
            frontend, gw, port = await _frontend(1)
            bad = (
                b"POST /api/v0.1/predictions HTTP/1.1\r\n"
                b"host: x\r\ncontent-length: 5_0\r\n\r\n"
            )
            resp = await self._raw(port, bad)
            await frontend.stop()
            return resp

        assert b"400" in run(go()).split(b"\r\n")[0]

    def test_conflicting_content_lengths_rejected(self):
        async def go():
            frontend, gw, port = await _frontend(1)
            bad = (
                b"POST /api/v0.1/predictions HTTP/1.1\r\n"
                b"host: x\r\ncontent-length: 3\r\ncontent-length: 5\r\n\r\nabc"
            )
            resp = await self._raw(port, bad)
            await frontend.stop()
            return resp

        assert b"400" in run(go()).split(b"\r\n")[0]

    def test_chunked_upload_rejected(self):
        async def go():
            frontend, gw, port = await _frontend(1)
            bad = (
                b"POST /api/v0.1/predictions HTTP/1.1\r\n"
                b"host: x\r\ntransfer-encoding: chunked\r\n\r\n"
            )
            resp = await self._raw(port, bad)
            await frontend.stop()
            return resp

        assert b"411" in run(go()).split(b"\r\n")[0]


class TestChunkedResponseSplice:
    """SSE-shaped chunked responses forward through the splice."""

    def test_chunked_stream_forwards(self):
        async def go():
            # an "engine" whose stream endpoint emits chunked SSE events
            async def stream(request):
                resp = web.StreamResponse()
                resp.content_type = "text/event-stream"
                resp.enable_chunked_encoding()
                await resp.prepare(request)
                for i in range(3):
                    await resp.write(f"data: tok{i}\n\n".encode())
                await resp.write_eof()
                return resp

            app = web.Application()
            app.router.add_post("/api/v0.1/predictions/stream", stream)
            engine = TestClient(TestServer(app))
            await engine.start_server()
            frontend, gw, port = await _frontend(engine.server.port)
            async with aiohttp.ClientSession() as s:
                tok = await _token(s, port)
                r = await s.post(
                    f"http://127.0.0.1:{port}/api/v0.1/predictions/stream",
                    data=b"{}",
                    headers={"Authorization": f"Bearer {tok}"},
                )
                body = await r.content.read()
                status = r.status
                await frontend.stop()
                await engine.close()
                return status, body

        status, body = run(go())
        assert status == 200
        assert body.count(b"data: tok") == 3


class TestUpstreamReplayCap:
    def test_engine_that_always_closes_yields_502(self):
        """ADVICE finding 3: an engine that answers by closing the
        connection must exhaust the replay budget (2) and fail the client
        with 502 — not connect/close-loop until the deadline reaper."""

        async def go():
            connects = []

            async def handle(reader, writer):
                connects.append(1)
                await reader.read(64)  # the request reached the engine
                writer.close()

            server = await asyncio.start_server(handle, "127.0.0.1", 0)
            eport = server.sockets[0].getsockname()[1]
            frontend, gw, port = await _frontend(eport)
            async with aiohttp.ClientSession() as s:
                tok = await _token(s, port)
                r = await s.post(
                    f"http://127.0.0.1:{port}/api/v0.1/predictions",
                    json={"data": {"ndarray": [[1.0]]}},
                    headers={"Authorization": f"Bearer {tok}"},
                )
                status = r.status
                body = await r.json()
            await frontend.stop()
            server.close()
            await server.wait_closed()
            return status, body, len(connects)

        status, body, connects = run(go())
        assert status == 502
        assert body["status"]["code"] == 502
        # initial attempt + exactly 2 replays
        assert connects == 3


class TestEvictedPoolFailsFast:
    def test_spawn_send_on_closed_pool_fails_job_promptly(self):
        """ADVICE finding 4: a connect that lands after the pool was
        evicted (deployment removed) must fail the downstream with a
        prompt 503, not silently drop the job until the 504 reaper."""
        from seldon_core_tpu.gateway.h1gateway import _Job, _UpstreamPool

        async def go():
            async def handle(reader, writer):
                await asyncio.sleep(5)

            server = await asyncio.start_server(handle, "127.0.0.1", 0)
            eport = server.sockets[0].getsockname()[1]
            fails = []

            class Down:
                def upstream_failed(self, reason, forwarded, status=503):
                    fails.append((reason, forwarded, status))

            pool = _UpstreamPool("127.0.0.1", eport, asyncio.get_running_loop())
            pool.closed = True  # evicted while the job was being dispatched
            job = _Job(Down(), b"POST /x HTTP/1.1\r\ncontent-length: 0\r\n\r\n", False)
            pending = _Job(Down(), b"POST /y HTTP/1.1\r\ncontent-length: 0\r\n\r\n", False)
            pool.pending.append(pending)
            pool.spawn_send(job)
            for _ in range(100):
                if len(fails) >= 2:
                    break
                await asyncio.sleep(0.02)
            server.close()
            await server.wait_closed()
            return fails

        fails = run(go())
        assert len(fails) == 2, f"job+pending must both fail promptly: {fails}"
        for reason, forwarded, _status in fails:
            assert reason == "deployment removed" and forwarded is False


class TestHeaderFieldNameStrictness:
    """ADVICE finding 1: the raw head splices onto a SHARED pipelined
    engine connection — header names that are not RFC 7230 tokens (and
    obs-fold continuations) are smuggling vectors and must be 400'd."""

    async def _raw_request(self, port: int, head_and_body: bytes) -> bytes:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(head_and_body)
        await writer.drain()
        data = await asyncio.wait_for(reader.read(4096), timeout=5)
        writer.close()
        return data

    def test_whitespace_before_colon_rejected(self):
        async def go():
            engine = await _engine_client()
            frontend, gw, port = await _frontend(engine.server.port)
            resp = await self._raw_request(
                port,
                b"POST /api/v0.1/predictions HTTP/1.1\r\n"
                b"Content-Length : 2\r\n\r\n{}",
            )
            await frontend.stop()
            await engine.close()
            return resp

        resp = run(go())
        assert resp.startswith(b"HTTP/1.1 400"), resp[:64]

    def test_obs_fold_continuation_rejected(self):
        async def go():
            engine = await _engine_client()
            frontend, gw, port = await _frontend(engine.server.port)
            resp = await self._raw_request(
                port,
                b"POST /api/v0.1/predictions HTTP/1.1\r\n"
                b"x-first: a\r\n"
                b" folded-continuation\r\n"
                b"content-length: 2\r\n\r\n{}",
            )
            await frontend.stop()
            await engine.close()
            return resp

        resp = run(go())
        assert resp.startswith(b"HTTP/1.1 400"), resp[:64]

    def test_control_chars_in_name_rejected(self):
        async def go():
            engine = await _engine_client()
            frontend, gw, port = await _frontend(engine.server.port)
            resp = await self._raw_request(
                port,
                b"POST /api/v0.1/predictions HTTP/1.1\r\n"
                b"x\x01bad: a\r\ncontent-length: 2\r\n\r\n{}",
            )
            await frontend.stop()
            await engine.close()
            return resp

        resp = run(go())
        assert resp.startswith(b"HTTP/1.1 400"), resp[:64]


class TestSpliceBackpressure:
    """ADVICE r5 item 2: bounded buffering in BOTH directions of the
    splice — a client pipelining ahead of its response parks in the
    kernel buffer (pause_reading), and a fast engine stream toward a slow
    client pauses the ENGINE conn's reads instead of buffering unboundedly
    in the gateway."""

    def test_pipelined_flood_pauses_downstream_reads(self):
        async def go():
            release = asyncio.Event()

            async def handle(reader, writer):
                await reader.readuntil(b"\r\n\r\n")
                await release.wait()
                writer.write(
                    b"HTTP/1.1 200 OK\r\ncontent-type: application/json\r\n"
                    b"content-length: 2\r\n\r\n{}"
                )
                await writer.drain()

            server = await asyncio.start_server(handle, "127.0.0.1", 0)
            eport = server.sockets[0].getsockname()[1]
            frontend, gw, port = await _frontend(eport)
            async with aiohttp.ClientSession() as s:
                tok = await _token(s, port)
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(
                b"POST /api/v0.1/predictions HTTP/1.1\r\n"
                + f"authorization: Bearer {tok}\r\n".encode()
                + b"content-length: 2\r\n\r\n{}"
            )
            await writer.drain()
            # flood 1MB of pipelined bytes while the response is pending
            junk = b"X" * (1 << 20)
            writer.write(junk)
            paused_conn = None
            for _ in range(200):
                await asyncio.sleep(0.01)
                for conn in frontend._conns:
                    if conn._read_paused:
                        paused_conn = conn
                        break
                if paused_conn is not None:
                    break
            buffered = len(paused_conn.buf) if paused_conn is not None else -1
            release.set()
            data = await asyncio.wait_for(reader.read(200), timeout=5)
            writer.close()
            await frontend.stop()
            server.close()
            await server.wait_closed()
            return paused_conn is not None, buffered, data

        paused, buffered, data = run(go())
        assert paused, "flooded conn never paused its reads"
        # the gateway buffered at most the cap + one read chunk, not the 1MB
        assert 0 <= buffered < (1 << 19), buffered
        assert data.startswith(b"HTTP/1.1 200")

    def test_fast_engine_stream_pauses_upstream_reads(self):
        async def go():
            total = 4 * (1 << 20)  # 4MB content-length-framed response

            async def handle(reader, writer):
                await reader.readuntil(b"\r\n\r\n")
                writer.write(
                    b"HTTP/1.1 200 OK\r\ncontent-type: application/octet-stream\r\n"
                    + b"content-length: %d\r\n\r\n" % total
                )
                chunk = b"Y" * (1 << 16)
                for _ in range(total // len(chunk)):
                    writer.write(chunk)
                    await writer.drain()
                await writer.drain()

            server = await asyncio.start_server(handle, "127.0.0.1", 0)
            eport = server.sockets[0].getsockname()[1]
            frontend, gw, port = await _frontend(eport)
            async with aiohttp.ClientSession() as s:
                tok = await _token(s, port)
            import socket as _socket

            sock = _socket.socket()
            # tiny client receive buffer: the kernel must not absorb the
            # whole stream, or the gateway-side pause never has to fire
            sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, 8192)
            sock.connect(("127.0.0.1", port))
            reader, writer = await asyncio.open_connection(sock=sock, limit=1 << 16)
            writer.write(
                b"POST /api/v0.1/predictions HTTP/1.1\r\n"
                + f"authorization: Bearer {tok}\r\n".encode()
                + b"content-length: 2\r\n\r\n{}"
            )
            await writer.drain()
            # force the downstream transport to signal fullness early
            for _ in range(100):
                await asyncio.sleep(0.01)
                if frontend._conns:
                    for c in frontend._conns:
                        if c.transport is not None:
                            c.transport.set_write_buffer_limits(high=4096)
                    break
            # do NOT read: the gateway's downstream buffer must fill and
            # propagate the pause to the ENGINE connection
            saw_pause = False
            for _ in range(500):
                await asyncio.sleep(0.01)
                if any(c._write_paused for c in frontend._conns):
                    saw_pause = True
                    break
            # now drain everything; the stream must complete intact
            got = 0
            head = await asyncio.wait_for(
                reader.readuntil(b"\r\n\r\n"), timeout=10
            )
            while got < total:
                blob = await asyncio.wait_for(reader.read(1 << 20), timeout=10)
                if not blob:
                    break
                got += len(blob)
            writer.close()
            await frontend.stop()
            server.close()
            await server.wait_closed()
            return saw_pause, head, got

        saw_pause, head, got = run(go())
        assert saw_pause, "fast engine stream never paused upstream reads"
        assert head.startswith(b"HTTP/1.1 200")
        assert got == 4 * (1 << 20), got
