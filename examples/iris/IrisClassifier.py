"""Iris classifier — the smallest end-to-end example (CPU, no TPU needed).

A user model is any class with ``predict(X, feature_names)``; this one is a
tiny closed-form logistic-regression-style scorer so the example has zero
training-time dependencies (the reference's sklearn_iris example pickles a
fitted sklearn model instead — same serving contract either way;
reference: examples/models/sklearn_iris/).
"""

import numpy as np

# hand-fitted coefficients for the classic iris problem (rows: setosa,
# versicolor, virginica; cols: sepal_l, sepal_w, petal_l, petal_w, bias)
_W = np.array(
    [
        [0.4, 1.4, -2.2, -1.0, 0.3],
        [0.4, -1.6, 0.4, -1.3, 1.2],
        [-1.7, -1.5, 2.4, 2.4, -1.0],
    ]
)


class IrisClassifier:
    class_names = ["setosa", "versicolor", "virginica"]

    def predict(self, X, feature_names):
        X = np.atleast_2d(np.asarray(X, dtype=float))
        logits = X @ _W[:, :4].T + _W[:, 4]
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)
