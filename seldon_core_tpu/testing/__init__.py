"""Contract-based testing tools.

The pre-deploy story of the reference (reference:
wrappers/testing/tester.py:42-105 — random batches generated from a
``contract.json``, POSTed at a locally-running wrapped model) and the
post-deploy story (reference: util/api_tester/api-tester.py:44-61 — same
generator through the gateway with OAuth), rebuilt as a library + two CLIs:

    sct-tester      contract.json host port   # microservice (REST/gRPC)
    sct-api-tester  contract.json host port --oauth-key k --oauth-secret s

Improvements over the reference: seeded generators (reproducible batches),
response validation against the contract's ``targets``, latency stats, and a
process exit code that reflects failures (the reference always exits 0).
"""

from seldon_core_tpu.testing.contract import Contract, FeatureDef
from seldon_core_tpu.testing.tester import (
    ApiTester,
    MicroserviceTester,
    TestReport,
)

__all__ = [
    "Contract",
    "FeatureDef",
    "MicroserviceTester",
    "ApiTester",
    "TestReport",
]
