"""JAX execution plane.

The reference's "executor" is a Tomcat thread pool calling user Python over
HTTP per request (reference: engine/.../PredictiveUnitBean.java:68-112 +
wrappers/python/model_microservice.py:40-84).  Here the execution plane is:

* :class:`CompiledModel` — a jit/pjit-compiled forward function with params
  resident in TPU HBM, bucketed batch padding so serving never recompiles,
* :class:`BatchQueue` — a continuous micro-batching queue turning concurrent
  single requests into large MXU-friendly device steps,
* :class:`JaxModelComponent` — the adapter that makes a compiled model a
  graph unit (``predict``) so it drops into any inference graph.
"""

from seldon_core_tpu.executor.compiled import BucketSpec, CompiledModel
from seldon_core_tpu.executor.batcher import BatchQueue
from seldon_core_tpu.executor.checkpoint import load_params, save_params
from seldon_core_tpu.executor.component import JaxModelComponent
from seldon_core_tpu.executor.lora import AdapterPool, AdapterPoolFull
from seldon_core_tpu.executor.memory import (
    MEMORY,
    HBMOverCommit,
    MemoryManager,
)

__all__ = [
    "BucketSpec",
    "CompiledModel",
    "BatchQueue",
    "JaxModelComponent",
    "load_params",
    "save_params",
    "AdapterPool",
    "AdapterPoolFull",
    "MemoryManager",
    "MEMORY",
    "HBMOverCommit",
]
