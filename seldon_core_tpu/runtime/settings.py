"""Central registry of every ``SCT_*`` environment variable.

Deliberately stdlib-only and import-light: the operator's control plane,
the sctlint static analyzer, and the docs generator all need the full
knob table without pulling the JAX runtime (the same constraint as
utils/mesh_contract.py).  Every env var the serving plane reads MUST be
declared here — sctlint's ``env-registry`` rule fails CI on a quoted
``SCT_*`` literal that has no declaration, and docs/CONFIG.md is
generated from this table (``python -m seldon_core_tpu.tools.sctlint
--write-config-docs`` after editing).

Call sites may keep their local ``os.environ.get`` idiom (registration
is the invariant, not the accessor), but new code should prefer the
typed getters below so default + type live in exactly one place.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

__all__ = [
    "Setting",
    "REGISTRY",
    "declare",
    "get_raw",
    "get_str",
    "get_int",
    "get_float",
    "get_bool",
    "markdown_table",
]


@dataclass(frozen=True)
class Setting:
    """One declared env var: its textual default (exactly the string the
    call site would pass to ``os.environ.get``; ``None`` = unset means
    feature off / value absent), coarse type, and a one-line doc."""

    name: str
    default: str | None
    type: str  # "str" | "int" | "float" | "bool" | "csv"
    doc: str
    section: str


REGISTRY: dict[str, Setting] = {}

# values get_bool treats as false; anything else (incl. bare "set") is true
_FALSY = ("", "0", "false", "off", "no")


def declare(
    name: str,
    default: str | None,
    type: str,
    doc: str,
    *,
    section: str = "general",
) -> Setting:
    if name in REGISTRY:
        raise ValueError(f"duplicate setting declaration: {name}")
    if not name.startswith("SCT_"):
        raise ValueError(f"settings registry is for SCT_* vars, got {name}")
    s = Setting(name, default, type, doc, section)
    REGISTRY[name] = s
    return s


def _lookup(name: str) -> Setting:
    try:
        return REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"{name} is not declared in seldon_core_tpu.runtime.settings; "
            "declare() it (sctlint env-registry enforces this)"
        ) from None


def get_raw(name: str, environ=None) -> str | None:
    """The raw env string, falling back to the declared default."""
    env = os.environ if environ is None else environ
    s = _lookup(name)
    v = env.get(name)
    return s.default if v is None else v


def get_str(name: str, environ=None) -> str | None:
    v = get_raw(name, environ)
    return v if v else _lookup(name).default


def get_int(name: str, environ=None) -> int:
    s = _lookup(name)
    v = get_raw(name, environ)
    try:
        return int(v or s.default or 0)
    except ValueError:
        return int(s.default or 0)


def get_float(name: str, environ=None) -> float:
    s = _lookup(name)
    v = get_raw(name, environ)
    try:
        return float(v or s.default or 0.0)
    except ValueError:
        return float(s.default or 0.0)


def get_bool(name: str, environ=None) -> bool:
    v = get_raw(name, environ)
    return (v if v is not None else "").strip().lower() not in _FALSY


# ---------------------------------------------------------------------------
# declarations
# ---------------------------------------------------------------------------

# -- execution plane: generation scheduler + compiled programs --------------
declare("SCT_GEN_OVERLAP", "1", "bool",
        "Overlapped decode pipeline: dispatch block N+1 before fetching "
        "block N (docs/PERFORMANCE.md).",
        section="executor")
declare("SCT_GEN_QUEUE_MAX", "256", "int",
        "Generation admission queue depth before overflow shedding.",
        section="executor")
declare("SCT_BATCH_PIPELINE", "8", "int",
        "Micro-batch pipeline depth for the non-generative batcher.",
        section="executor")
declare("SCT_BATCH_QUEUE_MAX", "2048", "int",
        "Batcher queue depth before overflow shedding.",
        section="executor")
declare("SCT_WARMUP_CONCURRENCY", "4", "int",
        "Threads compiling warmup program variants in parallel.",
        section="executor")
declare("SCT_WARMUP_SUFFIX", "1", "bool",
        "Warm suffix-prefill programs (per prefix-window bucket) at boot.",
        section="executor")
declare("SCT_SPEC_DRAFT", "0", "int",
        "Self-speculative draft length per verify pass (0 = speculation "
        "off; docs/PERFORMANCE.md §6).",
        section="executor")
declare("SCT_SPEC_NGRAM", "3", "int",
        "N-gram order of the on-device draft history ring.",
        section="executor")
declare("SCT_SPEC_METHOD", "ngram", "str",
        "Speculative proposer when SCT_SPEC_DRAFT > 0: ``ngram`` (history "
        "ring), ``heads`` (fused Medusa-style decode heads), or ``draft`` "
        "(co-resident draft model; docs/PERFORMANCE.md §6).",
        section="executor")
declare("SCT_SPEC_HEADS", "0", "int",
        "Medusa-style head count for ``heads`` speculation (0 = match "
        "SCT_SPEC_DRAFT; must be >= the draft length).",
        section="executor")
declare("SCT_SPEC_HEADS_PATH", None, "str",
        "Checkpoint directory for trained speculation heads (unset = "
        "synthesize from the base lm_head; executor/checkpoint.py layout).",
        section="executor")
declare("SCT_SPEC_DRAFT_MODEL", "truncate:auto", "str",
        "Draft model geometry for ``draft`` speculation: ``truncate:N`` "
        "(first N base layers), ``truncate:auto``, or ``preset:NAME`` "
        "(family preset sharing the base vocab).",
        section="executor")
declare("SCT_PREFILL_CHUNK", "0", "int",
        "Chunked-prefill chunk size in tokens (0 = monolithic prefill; "
        "docs/PERFORMANCE.md §7).",
        section="executor")
declare("SCT_DECODE_KERNEL", "0", "bool",
        "Use the Pallas paged decode-attention kernel "
        "(ops/paged_attention.py) instead of the dense gather path.",
        section="executor")
declare("SCT_KV_DTYPE", None, "str",
        "Paged-KV quantization dtype (``int8``; unset = model dtype).",
        section="executor")

# -- multi-LoRA adapter plane ----------------------------------------------
declare("SCT_LORA_RANK", "0", "int",
        "LoRA adapter rank (0 = multi-LoRA plane off; docs/MULTITENANT.md).",
        section="lora")
declare("SCT_LORA_SLOTS", "8", "int",
        "HBM adapter-pool slots (stacked A/B factors) per deployment.",
        section="lora")
declare("SCT_LORA_TARGETS", "qkvo", "str",
        "Projection set adapters apply to (subset of ``qkvo``).",
        section="lora")
declare("SCT_LORA_ADAPTERS", None, "csv",
        "Adapters to register at boot: ``name[:seed]`` comma list.",
        section="lora")

# -- HBM + host-DRAM memory ledgers ----------------------------------------
declare("SCT_HBM_GB", "16", "float",
        "Per-chip HBM budget the MemoryManager arbitrates (GiB).",
        section="memory")
declare("SCT_HBM_ENFORCE", "0", "bool",
        "Reject deployment builds whose reservation exceeds the HBM "
        "budget (HBMOverCommit) instead of logging.",
        section="memory")
declare("SCT_PREFIX_DRAM_GB", "0", "float",
        "Host-DRAM pool for demoted prefix KV blocks (GiB, 0 = tier "
        "off; docs/CACHING.md).",
        section="memory")
declare("SCT_PACK_SUSPEND_GB", "1", "float",
        "Host-DRAM budget for preemption suspend records (GiB; "
        "docs/PACKING.md).",
        section="memory")

# -- prefix cache + response cache -----------------------------------------
declare("SCT_CACHE_PREFIX", "0", "bool",
        "Radix prefix-KV reuse across admissions (docs/CACHING.md).",
        section="cache")
declare("SCT_PREFIX_PEER_PULL", "0", "bool",
        "Pull hot prefix KV from the peer replica advertising it instead "
        "of re-prefilling (docs/CACHING.md tiers).",
        section="cache")
declare("SCT_CACHE", "0", "bool",
        "Gateway response cache + single-flight collapser.",
        section="cache")
declare("SCT_CACHE_DEPLOYMENTS", None, "csv",
        "Restrict the response cache to these deployments (empty = all).",
        section="cache")
declare("SCT_CACHE_MAX_ENTRIES", "4096", "int",
        "Response-cache entry cap.",
        section="cache")
declare("SCT_CACHE_MAX_BYTES", "67108864", "int",
        "Response-cache byte cap.",
        section="cache")
declare("SCT_CACHE_TTL_S", "60", "float",
        "Response-cache entry TTL (seconds).",
        section="cache")
declare("SCT_SEMCACHE", "0", "bool",
        "Semantic cache tier: cosine-similarity hits over pooled prompt "
        "embeddings (needs SCT_EMBED on the unit; docs/CACHING.md).",
        section="cache")
declare("SCT_SEMCACHE_SIM", "0.95", "float",
        "Cosine-similarity threshold for a semantic cache hit.",
        section="cache")
declare("SCT_SEMCACHE_MAX_ENTRIES", "2048", "int",
        "Semantic-cache entry cap.",
        section="cache")
declare("SCT_SEMCACHE_MAX_BYTES", "33554432", "int",
        "Semantic-cache byte cap (vectors + cached response bytes).",
        section="cache")
declare("SCT_SEMCACHE_TTL_S", "300", "float",
        "Semantic-cache entry TTL (seconds).",
        section="cache")

# -- LLM inference graphs (docs/GRAPHS.md) ----------------------------------
declare("SCT_EMBED", "0", "bool",
        "Pooled-embedding path on generative units: POST /embeddings + "
        "the semantic cache tier's vector source (docs/GRAPHS.md).",
        section="graphllm")
declare("SCT_CASCADE_CONF_SIGNAL", "0", "bool",
        "Fold the per-step top-2 logit margin into the fused decode "
        "programs so replies carry a confidence signal for cascade "
        "routing (zero extra host syncs; docs/GRAPHS.md).",
        section="graphllm")
declare("SCT_CASCADE_CONF", "2.0", "float",
        "Mean logit-margin threshold below which a cascade tier's answer "
        "is escalated to the next tier.",
        section="graphllm")
declare("SCT_CASCADE_TTFT_MS", "0", "float",
        "Expected next-tier TTFT: escalation is skipped when the "
        "remaining deadline budget is smaller (0 = gate off).",
        section="graphllm")
declare("SCT_GUARDRAIL_CLASS", "interactive", "str",
        "Default QoS class guardrail units re-seed for their downstream "
        "walk (``interactive``/``batch``; docs/GRAPHS.md).",
        section="graphllm")

# -- QoS admission (engine SCT_QOS_*, gateway SCT_GW_QOS_*) -----------------
for _pfx, _where in (("SCT_QOS", "engine"), ("SCT_GW_QOS", "gateway")):
    _default_enabled = "1" if _pfx == "SCT_QOS" else None
    declare(_pfx, _default_enabled, "bool",
            f"Enable the {_where} QoS admission controller "
            "(docs/QOS.md; engine defaults on, gateway off).",
            section="qos")
    declare(f"{_pfx}_MAX_INFLIGHT", "256", "int",
            f"{_where}: in-flight request cap before shedding.",
            section="qos")
    declare(f"{_pfx}_MAX_QUEUE", "512", "int",
            f"{_where}: admission queue cap before shedding.",
            section="qos")
    declare(f"{_pfx}_RATE", "0", "float",
            f"{_where}: token-bucket refill rate, requests/s (0 = off).",
            section="qos")
    declare(f"{_pfx}_BURST", "0", "float",
            f"{_where}: token-bucket burst size.",
            section="qos")
    declare(f"{_pfx}_INTERACTIVE_RESERVE", "0.5", "float",
            f"{_where}: fraction of capacity reserved for interactive "
            "traffic under brownout.",
            section="qos")
    declare(f"{_pfx}_DEFAULT_DEADLINE_MS", "0", "float",
            f"{_where}: deadline stamped on requests that carry none "
            "(0 = no default SLO).",
            section="qos")
    declare(f"{_pfx}_PREDICTIVE", "1", "bool",
            f"{_where}: predictive shedding off queue-wait EWMAs.",
            section="qos")
    declare(f"{_pfx}_BROWNOUT_SHED_RATE", "0.5", "float",
            f"{_where}: fraction of batch traffic shed during brownout.",
            section="qos")
    declare(f"{_pfx}_BROWNOUT_WINDOW_S", "5", "float",
            f"{_where}: decision window for entering brownout (seconds).",
            section="qos")
    declare(f"{_pfx}_BROWNOUT_COOLDOWN_S", "5", "float",
            f"{_where}: cooldown before leaving brownout (seconds).",
            section="qos")
    declare(f"{_pfx}_BROWNOUT_CLAMP_TOKENS", "16", "int",
            f"{_where}: max_tokens clamp applied during brownout.",
            section="qos")
declare("SCT_DEFAULT_DEADLINE_MS", "0", "float",
        "Gateway-wide default deadline for requests without one (ms).",
        section="qos")

# -- chip packing / device arbiter -----------------------------------------
declare("SCT_PACK", "0", "bool",
        "Auto-attach every GenerativeComponent to the shared device "
        "arbiter (docs/PACKING.md).",
        section="packing")
declare("SCT_PACK_SLO_MS", None, "float",
        "Interactive queue-wait SLO band for packed deployments (ms; "
        "unset = caller/per-deployment default).",
        section="packing")
declare("SCT_PACK_PREEMPT", "1.0", "float",
        "Preempt a batch co-resident when interactive pressure >= "
        "slo * this.",
        section="packing")
declare("SCT_PACK_RESUME", "0.5", "float",
        "Resume the preempted deployment when pressure < slo * this.",
        section="packing")

# -- disaggregated prefill/decode ------------------------------------------
declare("SCT_ENGINE_ROLE", None, "str",
        "Engine pool role: ``unified`` (default), ``prefill`` or "
        "``decode`` (docs/DISAGGREGATION.md).",
        section="disagg")
declare("SCT_DISAGG_DECODE", None, "csv",
        "Decode-pool upstream URLs a prefill engine hands off to "
        "(operator-injected).",
        section="disagg")
declare("SCT_DISAGG_TIMEOUT_S", "30", "float",
        "Prefill->decode handoff timeout before unified-local fallback.",
        section="disagg")

# -- gateway data plane -----------------------------------------------------
declare("SCT_REST_IMPL", "h1", "str",
        "Gateway REST server implementation (``h1`` native, ``aiohttp`` "
        "fallback).",
        section="gateway")
declare("SCT_GRPC_IMPL", None, "str",
        "gRPC transport (default native h2; ``grpcio`` falls back to "
        "grpc.aio).",
        section="gateway")
declare("SCT_GW_UPSTREAM_CONNS", "8", "int",
        "Pooled upstream connections per engine endpoint.",
        section="gateway")
declare("SCT_GW_PIPELINE_BUF", "65536", "int",
        "Per-connection pipelined-response buffer (bytes).",
        section="gateway")
declare("SCT_GW_ROUTE_POLL_S", "2", "float",
        "Replica /stats poll interval for prefix-affine routing (s).",
        section="gateway")
declare("SCT_GW_ROUTE_PREFIX", "1", "bool",
        "Longest-prefix-match replica routing over gossiped radix "
        "digests (docs/DISAGGREGATION.md routing).",
        section="gateway")
declare("SCT_GW_PEER_YIELD", "4", "int",
        "Peer-pull yield: decode admissions awaited per peer-prefix "
        "install.",
        section="gateway")

# -- resilience / chaos plane (docs/RESILIENCE.md) --------------------------
declare("SCT_CHAOS_PLAN", None, "str",
        "Deterministic fault-injection plan "
        "(``site:kind[:key=value...];...`` — see docs/RESILIENCE.md). "
        "Unset = chaos plane fully inert (production default).",
        section="resilience")
declare("SCT_CHAOS_SEED", "0", "int",
        "Seed for probabilistic chaos rules (``p=``): one seed replays "
        "the identical fault sequence.",
        section="resilience")
declare("SCT_GW_POLL_FAILS", "2", "int",
        "Consecutive failed /stats/cache polls before the router clears "
        "a replica's prefix digests (one dropped poll must not destroy "
        "prefix affinity).",
        section="resilience")
declare("SCT_GW_RETRY_BUDGET", "10", "float",
        "Per-deployment retry-budget burst: retries available to an "
        "idle deployment before the refill rate gates them.",
        section="resilience")
declare("SCT_GW_RETRY_RATE", "0.2", "float",
        "Retry-budget refill: retries earned per forwarded request "
        "(0.2 = at most ~20% retry amplification under sustained "
        "failure).",
        section="resilience")
declare("SCT_GW_RETRY_BACKOFF_MS", "25", "float",
        "Base delay of the gateway's jittered exponential retry "
        "backoff (ms).",
        section="resilience")
declare("SCT_GW_RETRY_BACKOFF_MAX_MS", "1000", "float",
        "Cap on the gateway's per-attempt retry backoff (ms).",
        section="resilience")
declare("SCT_GW_CB_FAILS", "3", "int",
        "Consecutive forward failures that eject a replica from p2c "
        "routing (circuit breaker opens).",
        section="resilience")
declare("SCT_GW_CB_EJECT_S", "5", "float",
        "Ejection window before an open circuit admits one half-open "
        "probe request.",
        section="resilience")
declare("SCT_WATCH_BACKOFF_MS", "50", "float",
        "Base delay of the watch-relist backoff after consecutive 410 "
        "Gone (storm damping in gateway/operator watchers).",
        section="resilience")
declare("SCT_WATCH_BACKOFF_MAX_MS", "5000", "float",
        "Cap on the watch-relist backoff (ms).",
        section="resilience")
declare("SCT_KUBE_RETRIES", "4", "int",
        "Apiserver request attempts on 429/5xx before the error "
        "surfaces (Retry-After honored, capped jittered backoff).",
        section="resilience")

# -- observability ----------------------------------------------------------
declare("SCT_TIMELINE", "1", "bool",
        "Per-request lifecycle timelines (GET /stats/timeline; "
        "docs/OBSERVABILITY.md).",
        section="observability")
declare("SCT_TIMELINE_MAX", "512", "int",
        "Retained request timelines (ring).",
        section="observability")
declare("SCT_TIMELINE_EVENTS", "256", "int",
        "Events per timeline before drop-counting.",
        section="observability")
declare("SCT_SPANS_RING", "2048", "int",
        "In-memory span ring size (/stats/spans).",
        section="observability")
declare("SCT_STAGE_RING", "8192", "int",
        "Per-stage latency sample ring size (/stats/breakdown).",
        section="observability")
declare("SCT_TRACE_SAMPLE", "1.0", "float",
        "Trace sampling fraction [0, 1].",
        section="observability")
declare("SCT_SPANS_BROKER", None, "str",
        "Span fan-out broker URL for cross-pool trace stitching "
        "(unset = local ring only).",
        section="observability")
declare("SCT_SPANS_EXPORT_QUEUE", "2048", "int",
        "Bounded span export queue (drops oldest beyond this).",
        section="observability")
declare("SCT_OTLP_ENDPOINT", None, "str",
        "OTLP/HTTP collector endpoint for span export (unset = off).",
        section="observability")
declare("SCT_OTLP_TIMEOUT_S", "1.0", "float",
        "OTLP export request timeout (seconds).",
        section="observability")
declare("SCT_LOOP_LAG_INTERVAL_S", "0.25", "float",
        "Event-loop lag probe interval (seconds).",
        section="observability")
declare("SCT_METER", "1", "bool",
        "Per-tenant usage metering: device time + tokens attributed to "
        "(deployment, adapter, qos) keys (GET /stats/usage; "
        "docs/OBSERVABILITY.md cost attribution).",
        section="observability")
declare("SCT_METER_MAX_KEYS", "512", "int",
        "Live usage-meter key rows (LRU; evictions fold counter-exactly "
        "into the `other` rollup).",
        section="observability")
declare("SCT_METER_TOP_K", "16", "int",
        "seldon_usage_* label rows exported per scrape before the "
        "`other` rollup row (bounded cardinality).",
        section="observability")
declare("SCT_METER_ADAPTER_LABELS", "32", "int",
        "Distinct adapter label values on per-adapter metric families "
        "(seldon_lora_tokens and friends) before new adapters roll up "
        "into `other`.",
        section="observability")
declare("SCT_METRICS_EXEMPLARS", "0", "bool",
        "Render /prometheus in OpenMetrics format with trace-id "
        "exemplars on hot-stage latency histograms (a p99 spike links "
        "to GET /stats/timeline?trace=).",
        section="observability")

# -- fleet telemetry (collector + SLO engine; docs/OBSERVABILITY.md) --------
declare("SCT_FLEET", "1", "bool",
        "Run the fleet collector (operator + gateway): per-deployment "
        "aggregation of replica /stats/* into GET /stats/fleet.",
        section="fleet")
declare("SCT_FLEET_POLL_S", "10", "float",
        "Fleet collector poll interval (seconds, jittered).",
        section="fleet")
declare("SCT_FLEET_JITTER", "0.2", "float",
        "Poll-interval jitter fraction [0, 1] so a replica set is never "
        "scraped in lockstep.",
        section="fleet")
declare("SCT_FLEET_TIMEOUT_S", "2.0", "float",
        "Per-replica scrape HTTP timeout (seconds).",
        section="fleet")
declare("SCT_FLEET_STALE_POLLS", "3", "int",
        "Polls without a successful scrape before a replica is marked "
        "stale and excluded from aggregates (not zeroed).",
        section="fleet")
declare("SCT_FLEET_FAIL_DAMP", "3", "int",
        "Consecutive scrape failures before the collector damps that "
        "replica (skips a growing number of polls, capped).",
        section="fleet")
declare("SCT_FLEET_HISTORY_SLOTS", "360", "int",
        "Slots per time-series ring per resolution (10s and 2min rings; "
        "bounded, drop-on-full).",
        section="fleet")
declare("SCT_FLEET_PORT", "9109", "int",
        "Stats port of the operator / standalone collector "
        "(GET /stats/fleet, GET /stats/slo).",
        section="fleet")
declare("SCT_SLO", "1", "bool",
        "Evaluate declared SLO objectives as multi-window burn rates.",
        section="fleet")
declare("SCT_SLO_DEFAULT", None, "str",
        "Fallback SLO spec (seldon.io/slo grammar) for deployments "
        "without the annotation (unset = no objectives).",
        section="fleet")
declare("SCT_SLO_FAST_WINDOW_S", "60", "float",
        "Fast burn-rate window (seconds) — pages quickly on hard "
        "outages.",
        section="fleet")
declare("SCT_SLO_SLOW_WINDOW_S", "600", "float",
        "Slow burn-rate window (seconds) — confirms sustained burn "
        "before paging.",
        section="fleet")
declare("SCT_SLO_PAGE_BURN", "14.0", "float",
        "Burn-rate threshold (x budget) that flips warn -> page when "
        "both windows exceed it.",
        section="fleet")
declare("SCT_SLO_WARN_BURN", "6.0", "float",
        "Burn-rate threshold (x budget) that flips ok -> warn when "
        "both windows exceed it.",
        section="fleet")

# -- elastic autoscaler (closed-loop pool scaling; docs/AUTOSCALING.md) -----
declare("SCT_SCALE", "1", "bool",
        "Run the autoscale reconciler in the operator (scaling still "
        "requires the seldon.io/autoscale annotation on a CR).",
        section="scale")
declare("SCT_SCALE_INTERVAL_S", "15", "float",
        "Autoscale reconcile interval (seconds); each tick reads the "
        "fleet collector's latest aggregates and decides per pool.",
        section="scale")
declare("SCT_SCALE_EWMA_ALPHA", "0.4", "float",
        "EWMA smoothing factor (0, 1] applied to every policy signal "
        "before threshold comparison (1 = no smoothing).",
        section="scale")
declare("SCT_SCALE_UP_AT", "1.0", "float",
        "Upper hysteresis edge: scale up when max signal pressure "
        "(smoothed value / declared target) reaches this.",
        section="scale")
declare("SCT_SCALE_DOWN_AT", "0.5", "float",
        "Lower hysteresis edge: scale down only when EVERY fresh signal "
        "pressure sits at or below this (the band between down and up "
        "edges never moves replicas).",
        section="scale")
declare("SCT_SCALE_UP_HOLD_S", "60", "float",
        "Dwell after a scale-up before the next scale-up decision.",
        section="scale")
declare("SCT_SCALE_DOWN_HOLD_S", "180", "float",
        "Dwell after any scale decision before a scale-down (shrink is "
        "drain-based and deliberately slower than growth).",
        section="scale")
declare("SCT_SCALE_LOOKAHEAD_S", "60", "float",
        "Slope lookahead horizon: a signal is projected forward this "
        "many seconds along its history-ring trend, so a steady ramp "
        "scales up BEFORE it crosses the target.",
        section="scale")
declare("SCT_SCALE_MAX_STEP", "2", "int",
        "Max replicas added by one scale-up decision (shrink is always "
        "one drained replica per decision).",
        section="scale")
declare("SCT_SCALE_STALE_S", "90", "float",
        "Signal freshness horizon: observations older than this never "
        "drive a decision (covers collector gaps and counter dips "
        "during replica churn).",
        section="scale")
declare("SCT_SCALE_WINDOW_S", "60", "float",
        "Window for counter-derived signals (windowed shed rate) read "
        "off the fleet history rings.",
        section="scale")
declare("SCT_SCALE_LEDGER", "256", "int",
        "Decision-ledger ring size served on GET /stats/autoscale "
        "(bounded, drops oldest).",
        section="scale")
declare("SCT_SCALE_DRAIN_TIMEOUT_S", "30", "float",
        "Per-victim POST /admin/drain timeout during drain-based "
        "shrink; a failed or refused drain aborts the decision.",
        section="scale")
declare("SCT_SCALE_DEFAULT", None, "str",
        "Fallback autoscale spec (seldon.io/autoscale grammar) for "
        "deployments without the annotation (unset = static pools).",
        section="scale")

# -- multi-host mesh boot contract (operator-injected; jax-free reader in
#    utils/mesh_contract.py) ------------------------------------------------
declare("SCT_NUM_PROCESSES", None, "int",
        "Process count of the multi-host mesh (operator-injected; unset "
        "= single-process).",
        section="mesh")
declare("SCT_PROCESS_ID", None, "int",
        "Explicit process index (else derived from the pod ordinal).",
        section="mesh")
declare("SCT_COORDINATOR_ADDRESS", None, "str",
        "Explicit jax.distributed coordinator address (else derived "
        "from the mesh Service DNS).",
        section="mesh")
declare("SCT_COORDINATOR_PORT", "8476", "int",
        "Coordinator port of the multi-host boot contract.",
        section="mesh")
declare("SCT_MESH_SERVICE", None, "str",
        "Headless Service name giving each pod stable DNS for mesh "
        "formation (operator-injected).",
        section="mesh")
declare("SCT_POD_NAME", None, "str",
        "Pod name whose StatefulSet ordinal becomes the process index "
        "(operator-injected).",
        section="mesh")


# ---------------------------------------------------------------------------
# docs generation (docs/CONFIG.md)
# ---------------------------------------------------------------------------

_SECTION_TITLES = {
    "executor": "Execution plane (scheduler, compiled programs)",
    "lora": "Multi-LoRA adapter plane",
    "memory": "HBM + host-DRAM memory ledgers",
    "cache": "Prefix + response caching",
    "graphllm": "LLM inference graphs (cascades, embeddings, guardrails)",
    "qos": "QoS admission (engine `SCT_QOS_*`, gateway `SCT_GW_QOS_*`)",
    "packing": "Chip packing / device arbiter",
    "disagg": "Disaggregated prefill/decode",
    "gateway": "Gateway data plane",
    "resilience": "Resilience / chaos plane",
    "observability": "Observability",
    "fleet": "Fleet telemetry (collector + SLO engine)",
    "scale": "Elastic autoscaler (policy + drain-based actuator)",
    "mesh": "Multi-host mesh boot contract",
    "general": "General",
}


def markdown_table() -> str:
    """docs/CONFIG.md, generated.  Regenerate with
    ``python -m seldon_core_tpu.tools.sctlint --write-config-docs``."""
    out = [
        "# Configuration reference — `SCT_*` environment variables",
        "",
        "<!-- GENERATED FILE — do not edit by hand.  Source of truth: "
        "seldon_core_tpu/runtime/settings.py; regenerate with "
        "`python -m seldon_core_tpu.tools.sctlint --write-config-docs` "
        "(CI's `make lint-check` fails when stale). -->",
        "",
        f"{len(REGISTRY)} variables.  Every `SCT_*` env var the serving "
        "plane reads is declared in "
        "`seldon_core_tpu/runtime/settings.py`; sctlint's `env-registry` "
        "rule fails CI on an undeclared read (docs/STATIC_ANALYSIS.md).",
        "",
    ]
    for section, title in _SECTION_TITLES.items():
        rows = [s for s in REGISTRY.values() if s.section == section]
        if not rows:
            continue
        out += [f"## {title}", "",
                "| Variable | Default | Type | Description |",
                "|---|---|---|---|"]
        for s in rows:
            default = "_(unset)_" if s.default is None else f"`{s.default}`"
            out.append(f"| `{s.name}` | {default} | {s.type} | {s.doc} |")
        out.append("")
    return "\n".join(out) + ""
