"""HPACK (RFC 7541) — header compression for the asyncio gRPC data plane.

Hand-rolled because the image ships no ``h2``/``hpack`` package, and because
the serving hot path needs far less than a general HTTP/2 stack: gRPC unary
traffic uses a handful of headers that, after the first request on a
connection, arrive almost entirely as 1-byte indexed fields — cheaper to
decode than HTTP/1.1 text.  (The reference's data planes are Java
Spring/Tomcat and grpc-java; Python grpcio's per-RPC overhead is what this
module exists to beat — see wire/h2grpc.py.)

Decoder: complete (static+dynamic tables, all literal forms, Huffman,
table-size updates).  Encoder: deliberately minimal — literal-without-
indexing with raw strings only, which every compliant peer must accept
(RFC 7541 §6.2.2) and which lets request/response header blocks be
precomputed byte templates.

Huffman code/length constants are RFC 7541 Appendix B data.
"""

from __future__ import annotations

import collections

HUFFMAN_CODES = (
    0x1ff8, 0x7fffd8, 0xfffffe2, 0xfffffe3, 0xfffffe4, 0xfffffe5, 0xfffffe6, 0xfffffe7,
    0xfffffe8, 0xffffea, 0x3ffffffc, 0xfffffe9, 0xfffffea, 0x3ffffffd, 0xfffffeb, 0xfffffec,
    0xfffffed, 0xfffffee, 0xfffffef, 0xffffff0, 0xffffff1, 0xffffff2, 0x3ffffffe, 0xffffff3,
    0xffffff4, 0xffffff5, 0xffffff6, 0xffffff7, 0xffffff8, 0xffffff9, 0xffffffa, 0xffffffb,
    0x14, 0x3f8, 0x3f9, 0xffa, 0x1ff9, 0x15, 0xf8, 0x7fa,
    0x3fa, 0x3fb, 0xf9, 0x7fb, 0xfa, 0x16, 0x17, 0x18,
    0x0, 0x1, 0x2, 0x19, 0x1a, 0x1b, 0x1c, 0x1d,
    0x1e, 0x1f, 0x5c, 0xfb, 0x7ffc, 0x20, 0xffb, 0x3fc,
    0x1ffa, 0x21, 0x5d, 0x5e, 0x5f, 0x60, 0x61, 0x62,
    0x63, 0x64, 0x65, 0x66, 0x67, 0x68, 0x69, 0x6a,
    0x6b, 0x6c, 0x6d, 0x6e, 0x6f, 0x70, 0x71, 0x72,
    0xfc, 0x73, 0xfd, 0x1ffb, 0x7fff0, 0x1ffc, 0x3ffc, 0x22,
    0x7ffd, 0x3, 0x23, 0x4, 0x24, 0x5, 0x25, 0x26,
    0x27, 0x6, 0x74, 0x75, 0x28, 0x29, 0x2a, 0x7,
    0x2b, 0x76, 0x2c, 0x8, 0x9, 0x2d, 0x77, 0x78,
    0x79, 0x7a, 0x7b, 0x7ffe, 0x7fc, 0x3ffd, 0x1ffd, 0xffffffc,
    0xfffe6, 0x3fffd2, 0xfffe7, 0xfffe8, 0x3fffd3, 0x3fffd4, 0x3fffd5, 0x7fffd9,
    0x3fffd6, 0x7fffda, 0x7fffdb, 0x7fffdc, 0x7fffdd, 0x7fffde, 0xffffeb, 0x7fffdf,
    0xffffec, 0xffffed, 0x3fffd7, 0x7fffe0, 0xffffee, 0x7fffe1, 0x7fffe2, 0x7fffe3,
    0x7fffe4, 0x1fffdc, 0x3fffd8, 0x7fffe5, 0x3fffd9, 0x7fffe6, 0x7fffe7, 0xffffef,
    0x3fffda, 0x1fffdd, 0xfffe9, 0x3fffdb, 0x3fffdc, 0x7fffe8, 0x7fffe9, 0x1fffde,
    0x7fffea, 0x3fffdd, 0x3fffde, 0xfffff0, 0x1fffdf, 0x3fffdf, 0x7fffeb, 0x7fffec,
    0x1fffe0, 0x1fffe1, 0x3fffe0, 0x1fffe2, 0x7fffed, 0x3fffe1, 0x7fffee, 0x7fffef,
    0xfffea, 0x3fffe2, 0x3fffe3, 0x3fffe4, 0x7ffff0, 0x3fffe5, 0x3fffe6, 0x7ffff1,
    0x3ffffe0, 0x3ffffe1, 0xfffeb, 0x7fff1, 0x3fffe7, 0x7ffff2, 0x3fffe8, 0x1ffffec,
    0x3ffffe2, 0x3ffffe3, 0x3ffffe4, 0x7ffffde, 0x7ffffdf, 0x3ffffe5, 0xfffff1, 0x1ffffed,
    0x7fff2, 0x1fffe3, 0x3ffffe6, 0x7ffffe0, 0x7ffffe1, 0x3ffffe7, 0x7ffffe2, 0xfffff2,
    0x1fffe4, 0x1fffe5, 0x3ffffe8, 0x3ffffe9, 0xffffffd, 0x7ffffe3, 0x7ffffe4, 0x7ffffe5,
    0xfffec, 0xfffff3, 0xfffed, 0x1fffe6, 0x3fffe9, 0x1fffe7, 0x1fffe8, 0x7ffff3,
    0x3fffea, 0x3fffeb, 0x1ffffee, 0x1ffffef, 0xfffff4, 0xfffff5, 0x3ffffea, 0x7ffff4,
    0x3ffffeb, 0x7ffffe6, 0x3ffffec, 0x3ffffed, 0x7ffffe7, 0x7ffffe8, 0x7ffffe9, 0x7ffffea,
    0x7ffffeb, 0xffffffe, 0x7ffffec, 0x7ffffed, 0x7ffffee, 0x7ffffef, 0x7fffff0, 0x3ffffee,
    0x3fffffff,
)

HUFFMAN_LENGTHS = (
    13, 23, 28, 28, 28, 28, 28, 28, 28, 24, 30, 28, 28, 30, 28, 28,
    28, 28, 28, 28, 28, 28, 30, 28, 28, 28, 28, 28, 28, 28, 28, 28,
    6, 10, 10, 12, 13, 6, 8, 11, 10, 10, 8, 11, 8, 6, 6, 6,
    5, 5, 5, 6, 6, 6, 6, 6, 6, 6, 7, 8, 15, 6, 12, 10,
    13, 6, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7, 7,
    7, 7, 7, 7, 7, 7, 7, 7, 8, 7, 8, 13, 19, 13, 14, 6,
    15, 5, 6, 5, 6, 5, 6, 6, 6, 5, 7, 7, 6, 6, 6, 5,
    6, 7, 6, 5, 5, 6, 7, 7, 7, 7, 7, 15, 11, 14, 13, 28,
    20, 22, 20, 20, 22, 22, 22, 23, 22, 23, 23, 23, 23, 23, 24, 23,
    24, 24, 22, 23, 24, 23, 23, 23, 23, 21, 22, 23, 22, 23, 23, 24,
    22, 21, 20, 22, 22, 23, 23, 21, 23, 22, 22, 24, 21, 22, 23, 23,
    21, 21, 22, 21, 23, 22, 23, 23, 20, 22, 22, 22, 23, 22, 22, 23,
    26, 26, 20, 19, 22, 23, 22, 25, 26, 26, 26, 27, 27, 26, 24, 25,
    19, 21, 26, 27, 27, 26, 27, 24, 21, 21, 26, 26, 28, 27, 27, 27,
    20, 24, 20, 21, 22, 21, 21, 23, 22, 22, 25, 25, 24, 24, 26, 23,
    26, 27, 26, 26, 27, 27, 27, 27, 27, 28, 27, 27, 27, 27, 27, 26,
    30,
)

# RFC 7541 Appendix A — the 61-entry static table.
STATIC_TABLE: tuple[tuple[bytes, bytes], ...] = (
    (b":authority", b""),
    (b":method", b"GET"),
    (b":method", b"POST"),
    (b":path", b"/"),
    (b":path", b"/index.html"),
    (b":scheme", b"http"),
    (b":scheme", b"https"),
    (b":status", b"200"),
    (b":status", b"204"),
    (b":status", b"206"),
    (b":status", b"304"),
    (b":status", b"400"),
    (b":status", b"404"),
    (b":status", b"500"),
    (b"accept-charset", b""),
    (b"accept-encoding", b"gzip, deflate"),
    (b"accept-language", b""),
    (b"accept-ranges", b""),
    (b"accept", b""),
    (b"access-control-allow-origin", b""),
    (b"age", b""),
    (b"allow", b""),
    (b"authorization", b""),
    (b"cache-control", b""),
    (b"content-disposition", b""),
    (b"content-encoding", b""),
    (b"content-language", b""),
    (b"content-length", b""),
    (b"content-location", b""),
    (b"content-range", b""),
    (b"content-type", b""),
    (b"cookie", b""),
    (b"date", b""),
    (b"etag", b""),
    (b"expect", b""),
    (b"expires", b""),
    (b"from", b""),
    (b"host", b""),
    (b"if-match", b""),
    (b"if-modified-since", b""),
    (b"if-none-match", b""),
    (b"if-range", b""),
    (b"if-unmodified-since", b""),
    (b"last-modified", b""),
    (b"link", b""),
    (b"location", b""),
    (b"max-forwards", b""),
    (b"proxy-authenticate", b""),
    (b"proxy-authorization", b""),
    (b"range", b""),
    (b"referer", b""),
    (b"refresh", b""),
    (b"retry-after", b""),
    (b"server", b""),
    (b"set-cookie", b""),
    (b"strict-transport-security", b""),
    (b"transfer-encoding", b""),
    (b"user-agent", b""),
    (b"vary", b""),
    (b"via", b""),
    (b"www-authenticate", b""),
)


class HpackError(Exception):
    pass


# ---------------------------------------------------------------------------
# Huffman decode: bit-walk over a tree built once from the RFC constants.
# Literal huffman values are rare on the hot path (indexed fields dominate
# after connection warmup), so simplicity wins over an FSM.
# ---------------------------------------------------------------------------

def _build_tree():
    # node = [left, right, symbol]; symbol None for internal nodes
    root = [None, None, None]
    for sym in range(256):  # 256 = EOS, never decoded to output
        code, length = HUFFMAN_CODES[sym], HUFFMAN_LENGTHS[sym]
        node = root
        for i in range(length - 1, -1, -1):
            bit = (code >> i) & 1
            nxt = node[bit]
            if nxt is None:
                nxt = [None, None, None]
                node[bit] = nxt
            node = nxt
        node[2] = sym
    return root


_HUFFMAN_TREE = _build_tree()


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    node = _HUFFMAN_TREE
    root = _HUFFMAN_TREE
    depth = 0
    ones = 0  # consecutive 1-bits on the current partial walk
    for byte in data:
        for i in (7, 6, 5, 4, 3, 2, 1, 0):
            bit = (byte >> i) & 1
            node = node[bit]
            depth += 1
            ones = ones + 1 if bit else 0
            if node is None:
                raise HpackError("invalid huffman sequence")
            if node[2] is not None:
                out.append(node[2])
                node = root
                depth = 0
                ones = 0
    # RFC 7541 §5.2: trailing bits must be a prefix of EOS (all ones) and
    # strictly shorter than 8 bits; anything else is a decoding error
    if depth > 7:
        raise HpackError("huffman padding longer than 7 bits")
    if depth and ones != depth:
        raise HpackError("huffman padding is not an EOS prefix")
    return bytes(out)


def huffman_encode(data: bytes) -> bytes:
    bits = 0
    nbits = 0
    out = bytearray()
    for byte in data:
        code, length = HUFFMAN_CODES[byte], HUFFMAN_LENGTHS[byte]
        bits = (bits << length) | code
        nbits += length
        while nbits >= 8:
            nbits -= 8
            out.append((bits >> nbits) & 0xFF)
    if nbits:
        # pad with EOS prefix (all ones)
        out.append(((bits << (8 - nbits)) | ((1 << (8 - nbits)) - 1)) & 0xFF)
    return bytes(out)


# ---------------------------------------------------------------------------
# Primitive integer / string codecs (RFC 7541 §5)
# ---------------------------------------------------------------------------

def encode_int(value: int, prefix_bits: int, flags: int = 0) -> bytes:
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes((flags | value,))
    out = bytearray((flags | limit,))
    value -= limit
    while value >= 128:
        out.append(0x80 | (value & 0x7F))
        value >>= 7
    out.append(value)
    return bytes(out)


def decode_int(data, pos: int, prefix_bits: int) -> tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise HpackError("truncated integer")
        byte = data[pos]
        pos += 1
        value += (byte & 0x7F) << shift
        shift += 7
        if not byte & 0x80:
            return value, pos
        if shift > 35:
            raise HpackError("integer overflow")


def _decode_string(data, pos: int) -> tuple[bytes, int]:
    if pos >= len(data):
        raise HpackError("truncated string")
    huff = bool(data[pos] & 0x80)
    length, pos = decode_int(data, pos, 7)
    end = pos + length
    if end > len(data):
        raise HpackError("truncated string body")
    raw = bytes(data[pos:end])
    return (huffman_decode(raw) if huff else raw), end


def encode_string(value: bytes) -> bytes:
    """Raw (non-huffman) string — used by the minimal encoder."""
    return encode_int(len(value), 7) + value


# ---------------------------------------------------------------------------
# Decoder with dynamic table
# ---------------------------------------------------------------------------

_ENTRY_OVERHEAD = 32  # RFC 7541 §4.1


class Decoder:
    def __init__(self, max_table_size: int = 4096):
        self._dynamic: collections.deque[tuple[bytes, bytes]] = collections.deque()
        self._size = 0
        # max_table_size is OUR advertised SETTINGS_HEADER_TABLE_SIZE — the
        # ceiling the peer's encoder (and its table-size-update opcodes)
        # must stay under
        self._max_size = max_table_size
        self._settings_max = max_table_size
        # pure-decode memo: steady-state peers (our own stateless encoder)
        # send byte-identical blocks every request; a decode that neither
        # read nor wrote the dynamic table is a pure function of the bytes
        # and can be replayed from this cache.  grpcio peers use incremental
        # indexing, which marks the decode impure and bypasses the cache.
        self._cache: dict[bytes, list[tuple[bytes, bytes]]] = {}
        self._pure = True

    def _set_max(self, value: int) -> None:
        if value > self._settings_max:
            raise HpackError("peer exceeded negotiated header table size")
        self._max_size = value
        self._evict()

    def _evict(self) -> None:
        while self._size > self._max_size and self._dynamic:
            name, value = self._dynamic.pop()
            self._size -= len(name) + len(value) + _ENTRY_OVERHEAD

    def _add(self, name: bytes, value: bytes) -> None:
        self._dynamic.appendleft((name, value))
        self._size += len(name) + len(value) + _ENTRY_OVERHEAD
        self._evict()

    def _lookup(self, index: int) -> tuple[bytes, bytes]:
        if index == 0:
            raise HpackError("index 0 is invalid")
        if index <= len(STATIC_TABLE):
            return STATIC_TABLE[index - 1]
        self._pure = False  # result depends on dynamic-table state
        dyn = index - len(STATIC_TABLE) - 1
        try:
            return self._dynamic[dyn]
        except IndexError:
            raise HpackError(f"dynamic table index {index} out of range") from None

    def decode_cached(self, block: bytes) -> list[tuple[bytes, bytes]]:
        """Memoized decode for repeat blocks.  The returned list is SHARED —
        callers must not mutate it."""
        hit = self._cache.get(block)
        if hit is not None:
            return hit
        self._pure = True
        headers = self.decode(block)
        if self._pure:
            if len(self._cache) >= 256:
                # clear-on-full: unique blocks (per-request traceparent)
                # must not permanently crowd out the hot repeat blocks
                self._cache.clear()
            self._cache[bytes(block)] = headers
        return headers

    def decode(self, block: bytes) -> list[tuple[bytes, bytes]]:
        headers: list[tuple[bytes, bytes]] = []
        pos = 0
        n = len(block)
        while pos < n:
            byte = block[pos]
            if byte & 0x80:  # indexed field
                index, pos = decode_int(block, pos, 7)
                headers.append(self._lookup(index))
            elif byte & 0x40:  # literal with incremental indexing
                self._pure = False  # mutates the dynamic table
                index, pos = decode_int(block, pos, 6)
                name = self._lookup(index)[0] if index else None
                if name is None:
                    name, pos = _decode_string(block, pos)
                value, pos = _decode_string(block, pos)
                self._add(name, value)
                headers.append((name, value))
            elif byte & 0x20:  # dynamic table size update
                self._pure = False  # mutates decoder state
                size, pos = decode_int(block, pos, 5)
                self._set_max(size)
            else:  # literal without indexing (0x00) / never indexed (0x10)
                index, pos = decode_int(block, pos, 4)
                name = self._lookup(index)[0] if index else None
                if name is None:
                    name, pos = _decode_string(block, pos)
                value, pos = _decode_string(block, pos)
                headers.append((name, value))
        return headers


# ---------------------------------------------------------------------------
# Minimal encoder: literal-without-indexing, static-table name refs where
# available.  Stateless -> header blocks are constant byte templates.
# ---------------------------------------------------------------------------

_STATIC_NAME_INDEX = {}
for _i, (_name, _value) in enumerate(STATIC_TABLE, start=1):
    _STATIC_NAME_INDEX.setdefault(_name, _i)
_STATIC_FULL_INDEX = {
    (_name, _value): _i
    for _i, (_name, _value) in enumerate(STATIC_TABLE, start=1)
    if _value
}


def encode_headers(headers: list[tuple[bytes, bytes]]) -> bytes:
    """Stateless encode: fully-indexed static matches, else literal without
    indexing (name ref when the static table has the name)."""
    out = bytearray()
    for name, value in headers:
        full = _STATIC_FULL_INDEX.get((name, value))
        if full is not None:
            out += encode_int(full, 7, 0x80)
            continue
        name_idx = _STATIC_NAME_INDEX.get(name, 0)
        out += encode_int(name_idx, 4, 0x00)
        if not name_idx:
            out += encode_string(name)
        out += encode_string(value)
    return bytes(out)
