"""Shared model utilities: path-pattern logical-axis annotation.

Sharding annotations are derived from param *paths* (e.g.
``.../attention/query/kernel``) with an ordered regex table per model —
params stay a plain pytree, no custom pytree classes.
"""

from __future__ import annotations

import re
from typing import Any

import jax


def annotate_params(params: Any, rules: list[tuple[str, tuple[str | None, ...] | None]]) -> Any:
    """Build a pytree of logical-axis tuples matching ``params``.

    ``rules`` is an ordered list of ``(path_regex, axes)``; first match wins;
    no match -> ``None`` (replicated).  Axis tuple length must equal the
    leaf's ndim (checked).
    """

    def _one(path, leaf):
        pathstr = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path
        )
        for pattern, axes in rules:
            if re.search(pattern, pathstr):
                if axes is not None and len(axes) != getattr(leaf, "ndim", len(axes)):
                    raise ValueError(
                        f"axes {axes} rank-mismatch param {pathstr} shape {leaf.shape}"
                    )
                return axes
        return None

    return jax.tree_util.tree_map_with_path(_one, params)


def param_count(params: Any) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))
