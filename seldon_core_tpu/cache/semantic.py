"""Semantic response-cache tier: hit on paraphrases, not just bytes.

The exact-match tier (cache/content.py) is defeated by a single changed
token.  This tier indexes NORMALIZED embedding vectors of the prompt
(produced by the deployment's own pooled-embedding path — the same model
that will answer, so "similar to the cache" means similar in the model's
own representation space) and serves a cached response when the cosine
similarity of the best match clears ``SCT_SEMCACHE_SIM``.

Invalidation mirrors the exact tier's two-layer story (docs/CACHING.md):

* every entry carries the deployment ``tag`` (spec-hash) it was stored
  under — a lookup only matches entries with the CALLER's current tag, so
  a rolling update makes stale entries unhittable by construction;
* the same flush listeners that drop a deployment's exact entries call
  :meth:`flush` here, so both tiers clear together (the per-namespace
  flush counter makes that observable on ``GET /stats/cache``).

Everything is O(entries-in-namespace) per lookup under one lock — a
brute-force dot product over a few thousand float32 vectors is
microseconds of numpy, far below the device step a hit avoids — and
memory is bounded by an entry count AND a byte budget (vectors + cached
response bytes), oldest-first eviction.

Hits are served BEFORE QoS admission like exact hits, marked
``x-sct-cache: semantic``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any

import numpy as np

from seldon_core_tpu.obs.metering import METER
from seldon_core_tpu.utils.metrics import DEFAULT as DEFAULT_METRICS


class _Entry:
    __slots__ = ("vec", "value", "nbytes", "expires", "tag", "status")

    def __init__(self, vec, value, nbytes, expires, tag, status):
        self.vec = vec
        self.value = value
        self.nbytes = nbytes
        self.expires = expires
        self.tag = tag
        self.status = status


class SemanticCache:
    """Namespaced cosine-similarity cache over normalized prompt vectors.

    ``namespace`` is the deployment (flush granularity), ``tag`` the
    spec-hash the entry was stored under (staleness granularity).
    """

    def __init__(
        self,
        sim_threshold: float = 0.95,
        max_entries: int = 2048,
        max_bytes: int = 32 * 1024 * 1024,
        ttl_s: float = 300.0,
    ):
        self.sim_threshold = float(sim_threshold)
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple[str, int], _Entry]" = OrderedDict()
        self._next_id = 0
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.expirations = 0
        self.flushes = 0
        self.flushes_by_ns: dict[str, int] = {}
        self.last_sim: float | None = None

    @staticmethod
    def _normalize(vec: np.ndarray) -> np.ndarray:
        vec = np.asarray(vec, np.float32).ravel()
        norm = float(np.linalg.norm(vec))
        if norm <= 0.0 or not np.isfinite(norm):
            return vec
        return vec / norm

    def _m(self, metric, *labels):
        try:
            return metric.labels(*labels) if labels else metric
        except Exception:  # metrics must never fail a request
            return None

    def lookup(self, namespace: str, vec: np.ndarray, tag: str) -> Any | None:
        """Best same-tag entry in ``namespace`` with cosine >= threshold,
        or None.  ``vec`` need not be pre-normalized."""
        q = self._normalize(vec)
        now = time.monotonic()
        with self._lock:
            best: tuple[float, tuple[str, int], _Entry] | None = None
            doomed: list[tuple[str, int]] = []
            for key, e in self._entries.items():
                if key[0] != namespace:
                    continue
                if now >= e.expires:
                    doomed.append(key)
                    continue
                if e.tag != tag:
                    # stored under an older spec-hash: unhittable (the
                    # flush listener will clear it; matching it would
                    # serve a pre-update answer)
                    continue
                if e.vec.shape != q.shape:
                    continue
                sim = float(e.vec @ q)
                if sim >= self.sim_threshold and (
                    best is None or sim > best[0]
                ):
                    best = (sim, key, e)
            for key in doomed:
                self.bytes -= self._entries.pop(key).nbytes
                self.expirations += 1
            if best is None:
                self.misses += 1
                self.last_sim = None
                m = self._m(DEFAULT_METRICS.semcache_misses, namespace)
                if m is not None:
                    m.inc()
                return None
            sim, key, entry = best
            self._entries.move_to_end(key)
            self.hits += 1
            self.last_sim = sim
            m = self._m(DEFAULT_METRICS.semcache_hits, namespace)
            if m is not None:
                m.inc()
            # cost attribution: a semantic hit is a request the tenant got
            # for free, same ledger row as the exact tier's hits
            METER.add(namespace, requests_cached=1)
            return entry.value

    def put(
        self,
        namespace: str,
        vec: np.ndarray,
        value: Any,
        tag: str,
        nbytes: int | None = None,
        status: int = 200,
    ) -> None:
        q = self._normalize(vec)
        if nbytes is None:
            nbytes = len(value) if isinstance(value, (bytes, bytearray)) else 0
        nbytes = int(nbytes) + int(q.nbytes)
        if nbytes > self.max_bytes:
            return  # bigger than the whole budget: uncacheable
        entry = _Entry(
            q, value, nbytes, time.monotonic() + self.ttl_s, tag, status
        )
        with self._lock:
            key = (namespace, self._next_id)
            self._next_id += 1
            self._entries[key] = entry
            self.bytes += entry.nbytes
            while self._entries and (
                len(self._entries) > self.max_entries
                or self.bytes > self.max_bytes
            ):
                _, evicted = self._entries.popitem(last=False)
                self.bytes -= evicted.nbytes
                self.evictions += 1
            self._set_gauges()

    def flush(self, namespace: str | None = None) -> int:
        """Drop one namespace's entries (spec change / deployment removal),
        or everything when ``namespace`` is None.  Per-namespace flush
        counts land in :attr:`flushes_by_ns` so the invalidation story is
        observable on /stats/cache."""
        with self._lock:
            if namespace is None:
                flushed_ns = {k[0] for k in self._entries}
                n = len(self._entries)
                self._entries.clear()
                self.bytes = 0
            else:
                doomed = [k for k in self._entries if k[0] == namespace]
                flushed_ns = {namespace} if doomed else set()
                n = len(doomed)
                for k in doomed:
                    self.bytes -= self._entries.pop(k).nbytes
            if n:
                self.flushes += 1
                for ns in flushed_ns:
                    self.flushes_by_ns[ns] = self.flushes_by_ns.get(ns, 0) + 1
            self._set_gauges()
            return n

    def _set_gauges(self) -> None:
        try:
            DEFAULT_METRICS.semcache_entries.set(len(self._entries))
            DEFAULT_METRICS.semcache_bytes.set(self.bytes)
        except Exception:
            pass

    def snapshot(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "tier": "semantic",
                "entries": len(self._entries),
                "bytes": self.bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "ttl_s": self.ttl_s,
                "sim_threshold": self.sim_threshold,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / total, 4) if total else None,
                "last_similarity": (
                    round(self.last_sim, 4) if self.last_sim is not None else None
                ),
                "evictions": self.evictions,
                "expirations": self.expirations,
                "flushes": self.flushes,
                "flushes_by_namespace": dict(self.flushes_by_ns),
            }


# -- env config --------------------------------------------------------------


def semcache_enabled(environ: dict | None = None) -> bool:
    env = environ if environ is not None else os.environ
    return env.get("SCT_SEMCACHE", "0") == "1"


def semantic_cache_from_env(environ: dict | None = None) -> SemanticCache | None:
    """A configured SemanticCache, or None when the tier is off
    (``SCT_SEMCACHE`` unset).  Knobs: ``SCT_SEMCACHE_SIM`` (default 0.95),
    ``SCT_SEMCACHE_MAX_ENTRIES`` (2048), ``SCT_SEMCACHE_MAX_BYTES``
    (32MiB), ``SCT_SEMCACHE_TTL_S`` (300)."""
    env = environ if environ is not None else os.environ
    if not semcache_enabled(env):
        return None
    return SemanticCache(
        sim_threshold=float(env.get("SCT_SEMCACHE_SIM", "0.95")),
        max_entries=int(env.get("SCT_SEMCACHE_MAX_ENTRIES", "2048")),
        max_bytes=int(env.get("SCT_SEMCACHE_MAX_BYTES", str(32 * 1024 * 1024))),
        ttl_s=float(env.get("SCT_SEMCACHE_TTL_S", "300")),
    )
