"""wire/h1client.py — the lean HTTP/1.1 forward pool: framing modes,
keep-alive recycling, stale-connection replay, and retry classification."""

import asyncio

import pytest
from aiohttp import web

from seldon_core_tpu.wire.h1client import H1ConnectError, H1Pool, H1SentError

run = asyncio.run


async def _server(handler):
    app = web.Application()
    app.router.add_post("/echo", handler)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = runner.addresses[0][1]
    return runner, port


class TestH1Pool:
    def test_roundtrip_and_keepalive_reuse(self):
        hits = []

        async def echo(request):
            hits.append(1)
            return web.json_response({"got": (await request.read()).decode()})

        async def go():
            runner, port = await _server(echo)
            pool = H1Pool("127.0.0.1", port)
            try:
                for i in range(5):
                    resp = await pool.post("/echo", f"b{i}".encode())
                    assert resp.status == 200
                    assert f"b{i}".encode() in resp.body
                # all five rode ONE recycled connection
                assert len(pool._idle) == 1
            finally:
                await pool.close()
                await runner.cleanup()

        run(go())

    def test_extra_headers_forwarded(self):
        async def echo(request):
            return web.json_response({"tp": request.headers.get("traceparent", "")})

        async def go():
            runner, port = await _server(echo)
            pool = H1Pool("127.0.0.1", port)
            try:
                resp = await pool.post(
                    "/echo", b"{}", headers={"traceparent": "00-aa-bb-01"}
                )
                assert b"00-aa-bb-01" in resp.body
            finally:
                await pool.close()
                await runner.cleanup()

        run(go())

    def test_stale_keepalive_replays_once(self):
        async def echo(request):
            return web.json_response({"ok": True})

        async def go():
            runner, port = await _server(echo)
            pool = H1Pool("127.0.0.1", port)
            try:
                resp = await pool.post("/echo", b"{}")
                assert resp.status == 200
                # poison the idle socket the way an upstream keep-alive
                # timeout would: close it under the pool
                _r, w = pool._idle[0]
                w.close()
                await asyncio.sleep(0.05)
                resp = await pool.post("/echo", b"{}")  # replays on fresh conn
                assert resp.status == 200
            finally:
                await pool.close()
                await runner.cleanup()

        run(go())

    def test_connect_refused_is_connect_error(self):
        async def go():
            pool = H1Pool("127.0.0.1", 1)  # nothing listens on port 1
            with pytest.raises(H1ConnectError):
                await pool.post("/echo", b"{}")

        run(go())

    def test_chunked_response(self):
        async def chunked(request):
            resp = web.StreamResponse()
            resp.enable_chunked_encoding()
            await resp.prepare(request)
            await resp.write(b"hello ")
            await resp.write(b"world")
            await resp.write_eof()
            return resp

        async def go():
            runner, port = await _server(chunked)
            pool = H1Pool("127.0.0.1", port)
            try:
                resp = await pool.post("/echo", b"{}")
                assert resp.body == b"hello world"
            finally:
                await pool.close()
                await runner.cleanup()

        run(go())

    def test_connection_close_response(self):
        async def close_after(request):
            resp = web.json_response({"bye": True})
            resp.headers["Connection"] = "close"
            return resp

        async def go():
            runner, port = await _server(close_after)
            pool = H1Pool("127.0.0.1", port)
            try:
                resp = await pool.post("/echo", b"{}")
                assert resp.status == 200 and b"bye" in resp.body
                assert pool._idle == []  # closed conns are not recycled
            finally:
                await pool.close()
                await runner.cleanup()

        run(go())

    def test_fresh_connection_death_is_sent_error(self):
        async def go():
            async def kill(reader, writer):
                await reader.read(64)  # request partially read, then die
                writer.close()

            server = await asyncio.start_server(kill, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            pool = H1Pool("127.0.0.1", port)
            try:
                with pytest.raises(H1SentError):
                    await pool.post("/echo", b"{}")
            finally:
                await pool.close()
                server.close()

        run(go())


class TestReplaySafety:
    """Replay is allowed ONLY when a reused conn died before any response
    byte; mid-response death must surface as H1SentError (the upstream may
    have processed the request — replaying would duplicate it)."""

    def test_mid_response_death_on_reused_conn_does_not_replay(self):
        async def go():
            served = {"n": 0}

            async def handler(reader, writer):
                # request 1: full response, keep-alive
                await reader.readuntil(b"\r\n\r\n")
                await reader.readexactly(2)  # body "{}"
                served["n"] += 1
                writer.write(
                    b"HTTP/1.1 200 OK\r\ncontent-length: 2\r\n\r\nok"
                )
                await writer.drain()
                # request 2 on the SAME conn: status line then death
                await reader.readuntil(b"\r\n\r\n")
                await reader.readexactly(2)
                served["n"] += 1
                writer.write(b"HTTP/1.1 200 OK\r\ncontent-length: 99\r\n\r\npart")
                await writer.drain()
                writer.close()

            server = await asyncio.start_server(handler, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            pool = H1Pool("127.0.0.1", port)
            try:
                resp = await pool.post("/echo", b"{}")
                assert resp.status == 200 and resp.body == b"ok"
                with pytest.raises(H1SentError):
                    await pool.post("/echo", b"{}")
                # the dead request was NOT replayed on a fresh connection
                assert served["n"] == 2
            finally:
                await pool.close()
                server.close()

        run(go())

    def test_timeout_covers_connect(self):
        import time

        async def go():
            # RFC 5737 TEST-NET address: SYN-blackholed or refused depending
            # on the network; whatever the failure mode, post() must fail
            # within the deadline (the point: connect is INSIDE the budget)
            pool = H1Pool("203.0.113.1", 81)
            t0 = time.monotonic()
            with pytest.raises((asyncio.TimeoutError, H1ConnectError, H1SentError)):
                await pool.post("/echo", b"{}", timeout=1.0)
            assert time.monotonic() - t0 < 5.0

        run(go())
