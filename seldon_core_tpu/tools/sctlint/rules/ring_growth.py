"""ring-growth: no unbounded appends into ring/history buffers.

The telemetry plane's time-series store (``obs/history.py``) and the span
rings hold the line on memory by PREALLOCATING fixed-capacity slots and
overwriting in place (drop-on-full) — zero allocation at steady state, no
growth under a scrape storm or a metric-name explosion.  One stray
``.append()`` into such a buffer silently converts it back into an
unbounded list, and the leak only shows up days later in a long-lived
operator or gateway.

Flagged in package code (tests excluded):

* ``<recv>.append(...)`` / ``.extend(...)`` / ``.insert(...)`` where the
  receiver's dotted name names a ring buffer (contains ``ring``,
  ``history``, ``hist``, or ``samples``);
* ``<name> = deque()`` **without** ``maxlen`` where the target names a
  ring buffer — an unbounded deque is the same leak one constructor
  earlier.

Legitimately bounded growth is annotated in place with the reason:
``# sct: ring-growth-ok <why this cannot grow without bound>`` (e.g. a
``deque(maxlen=...)`` that drops oldest, or a test-double event log whose
lifetime is one test run).
"""

from __future__ import annotations

import ast
from typing import Iterable

from seldon_core_tpu.tools.sctlint.core import Context, Finding, Rule, dotted

GROW_VERBS = {"append", "extend", "insert"}
RING_NAMES = ("ring", "history", "hist", "samples")


def _names_ring(name: str) -> bool:
    low = name.lower()
    return any(s in low for s in RING_NAMES)


def _deque_without_maxlen(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    fname = dotted(value.func)
    if fname not in ("deque", "collections.deque"):
        return False
    return not any(kw.arg == "maxlen" for kw in value.keywords)


def check(ctx: Context) -> Iterable[Finding]:
    out: list[Finding] = []
    for src in ctx.py:
        if src.tree is None or "/tools/sctlint/" in src.rel:
            continue
        if src.rel.startswith("tests/"):
            continue
        for n in ast.walk(src.tree):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
                recv = dotted(n.func.value)
                if n.func.attr in GROW_VERBS and recv and _names_ring(recv):
                    out.append(Finding(
                        "ring-growth", src.rel, n.lineno,
                        f"{recv}.{n.func.attr}() grows a ring/history "
                        "buffer without bound — record into preallocated "
                        "slots (obs/history._Ring) or annotate why growth "
                        "is bounded",
                        src.snippet(n.lineno),
                    ))
            elif isinstance(n, (ast.Assign, ast.AnnAssign)):
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                value = n.value
                if value is None or not _deque_without_maxlen(value):
                    continue
                for t in targets:
                    tname = dotted(t)
                    if tname and _names_ring(tname):
                        out.append(Finding(
                            "ring-growth", src.rel, n.lineno,
                            f"{tname} is a deque() with no maxlen — an "
                            "unbounded ring buffer; pass maxlen= or "
                            "annotate why growth is bounded",
                            src.snippet(n.lineno),
                        ))
                        break
    return out


RULE = Rule(
    id="ring-growth",
    summary="ring/history buffers never grow without bound",
    explain=__doc__,
    check=check,
)
