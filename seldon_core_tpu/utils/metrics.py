"""Prometheus metrics with the reference's tag vocabulary.

The reference exports `seldon_api_engine_server_requests_duration_seconds` /
`..._client_requests_...` histograms tagged with deployment / predictor /
model name+image+version (reference:
engine/src/main/resources/application.properties:4-8,
engine/.../metrics/SeldonRestTemplateExchangeTagsProvider.java:34-90) and
feedback/reward counters (PredictiveUnitBean.java:239-242).  Same metric
names here so existing Grafana dashboards keep working.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# OpenMetrics content type served by /prometheus when exemplar rendering
# is on (SCT_METRICS_EXEMPLARS); plain text exposition otherwise.
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)
PLAIN_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def exemplars_enabled() -> bool:
    from seldon_core_tpu.runtime import settings

    return settings.get_bool("SCT_METRICS_EXEMPLARS")


def observe_exemplar(hist, value: float, trace_id: str | None) -> None:
    """Observe ``value`` carrying an OpenMetrics exemplar that links the
    bucket to ``GET /stats/timeline?trace=<trace_id>`` when exemplar
    rendering is on.  Histogram stand-ins without exemplar support fall
    back to a plain observe."""
    if trace_id and exemplars_enabled():
        try:
            hist.observe(value, exemplar={"trace_id": trace_id})
            return
        except TypeError:
            pass
    hist.observe(value)


# label sets exported per seldon_usage_* field group (refresh_usage)
_USAGE_TOKEN_KINDS = (
    ("prefill", "tokens_prefill"),
    ("decode", "tokens_decode"),
    ("spec_accepted", "tokens_spec_accepted"),
    ("spec_accepted_ngram", "tokens_spec_accepted_ngram"),
    ("spec_accepted_heads", "tokens_spec_accepted_heads"),
    ("spec_accepted_draft", "tokens_spec_accepted_draft"),
    ("saved_hbm", "tokens_saved_hbm"),
    ("saved_dram", "tokens_saved_dram"),
    ("saved_peer", "tokens_saved_peer"),
    ("wasted", "tokens_wasted"),
)
_USAGE_REQ_OUTCOMES = (
    ("completed", "requests_completed"),
    ("shed", "requests_shed"),
    ("reaped", "requests_reaped"),
    ("cached", "requests_cached"),
)


class MetricsRegistry:
    """Per-process metrics registry for engine / gateway / microservice."""

    def __init__(self, registry: CollectorRegistry | None = None):
        self.registry = registry or CollectorRegistry()
        self.server_requests = Histogram(
            "seldon_api_engine_server_requests_duration_seconds",
            "Engine ingress request latency",
            ["deployment_name", "predictor_name", "service", "method", "code"],
            registry=self.registry,
            buckets=_BUCKETS,
        )
        self.client_requests = Histogram(
            "seldon_api_engine_client_requests_duration_seconds",
            "Per-graph-node downstream call latency",
            ["deployment_name", "predictor_name", "model_name", "model_image",
             "model_version", "method", "code"],
            registry=self.registry,
            buckets=_BUCKETS,
        )
        self.ingress_requests = Histogram(
            "seldon_api_ingress_server_requests_duration_seconds",
            "Gateway ingress request latency",
            ["principal", "deployment_name", "service", "method", "code"],
            registry=self.registry,
            buckets=_BUCKETS,
        )
        self.feedback = Counter(
            "seldon_api_model_feedback",
            "Feedback events per unit",
            ["deployment_name", "predictor_name", "model_name"],
            registry=self.registry,
        )
        self.feedback_reward = Counter(
            "seldon_api_model_feedback_reward",
            "Accumulated reward per unit",
            ["deployment_name", "predictor_name", "model_name"],
            registry=self.registry,
        )
        self.custom_counter = Counter(
            "seldon_model_custom_counter",
            "User-code emitted counter metrics (Meta.metrics extension)",
            ["deployment_name", "predictor_name", "model_name", "key"],
            registry=self.registry,
        )
        self.custom_gauge = Gauge(
            "seldon_model_custom_gauge",
            "User-code emitted gauge metrics",
            ["deployment_name", "predictor_name", "model_name", "key"],
            registry=self.registry,
        )
        self.custom_timer = Histogram(
            "seldon_model_custom_timer",
            "User-code emitted timer metrics (seconds)",
            ["deployment_name", "predictor_name", "model_name", "key"],
            registry=self.registry,
            buckets=_BUCKETS,
        )
        self.batch_size = Histogram(
            "seldon_executor_batch_size",
            "Continuous-batching effective batch sizes",
            ["model_name"],
            registry=self.registry,
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
        )
        self.queue_depth = Gauge(
            "seldon_executor_queue_depth",
            "Continuous-batching queue depth",
            ["model_name"],
            registry=self.registry,
        )
        # TPU-serving vocabulary (fed by executor/batcher.py and
        # executor/generation.py; MFU peak comes from utils/roofline.py)
        self.queue_wait = Histogram(
            "seldon_executor_queue_wait_seconds",
            "Time a request waited in the batching queue before its device step",
            ["model_name"],
            registry=self.registry,
            buckets=_BUCKETS,
        )
        self.device_step = Histogram(
            "seldon_executor_device_step_seconds",
            "Device step round-trip time (dispatch through result fetch)",
            ["model_name"],
            registry=self.registry,
            buckets=_BUCKETS,
        )
        self.mfu = Gauge(
            "seldon_executor_mfu",
            "Model FLOP/s utilization of the most recent device step "
            "(achieved/chip peak; absent off-TPU)",
            ["model_name"],
            registry=self.registry,
        )
        self.ttft = Histogram(
            "seldon_generative_ttft_seconds",
            "Generative time-to-first-token (submit to first sampled token)",
            ["model_name"],
            registry=self.registry,
            buckets=_BUCKETS,
        )
        self.itl = Histogram(
            "seldon_itl_seconds",
            "Generative per-slot inter-token latency (delivery gap per "
            "fetched block over the tokens it carried) — prefill-induced "
            "decode stalls land here, invisible to TTFT/device-step",
            ["model_name"],
            registry=self.registry,
            buckets=_BUCKETS,
        )
        self.generated_tokens = Counter(
            "seldon_generative_tokens_total",
            "Generated tokens (rate() gives sustained tokens/s)",
            ["model_name"],
            registry=self.registry,
        )
        self.tokens_per_s = Gauge(
            "seldon_generative_tokens_per_s",
            "Per-request decode rate of the most recently completed generation",
            ["model_name"],
            registry=self.registry,
        )
        # QoS plane vocabulary (fed by qos/admission.py and the bounded
        # queues in executor/batcher.py + executor/generation.py)
        self.qos_admitted = Counter(
            "seldon_qos_admitted_total",
            "Requests admitted past QoS admission control",
            ["name", "priority"],
            registry=self.registry,
        )
        self.qos_shed = Counter(
            "seldon_qos_shed_total",
            "Requests shed by QoS admission control, by reason",
            ["name", "reason", "priority"],
            registry=self.registry,
        )
        self.qos_deadline_miss = Counter(
            "seldon_qos_deadline_miss_total",
            "Requests dropped by a queue because their deadline expired "
            "before a device step was spent on them",
            ["name", "stage"],
            registry=self.registry,
        )
        self.qos_inflight = Gauge(
            "seldon_qos_inflight",
            "Requests currently admitted (running + queued) per deployment",
            ["name"],
            registry=self.registry,
        )
        self.qos_brownout = Gauge(
            "seldon_qos_brownout",
            "1 while the deployment rides out sustained overload in "
            "brownout mode (batch shed, max_new_tokens clamped)",
            ["name"],
            registry=self.registry,
        )
        # Wire-throughput accounting (fed by obs/wire.py WireCounters on
        # every transport edge; docs/OBSERVABILITY.md "wire accounting")
        self.wire_bytes = Counter(
            "seldon_wire_bytes",
            "Bytes moved per transport edge (server edges: in=request, "
            "out=response; client edges: out=request sent, in=reply)",
            ["stage", "name", "direction"],
            registry=self.registry,
        )
        self.wire_requests = Counter(
            "seldon_wire_requests",
            "Transfers per transport edge",
            ["stage", "name"],
            registry=self.registry,
        )
        self.wire_mb_s = Gauge(
            "seldon_wire_mb_per_s",
            "Achieved wire MB/s EWMA per transport edge (per-transfer "
            "bytes/duration where the edge times the transfer)",
            ["stage", "name"],
            registry=self.registry,
        )
        # Always-on perf probes (obs/probes.py)
        self.eventloop_lag = Gauge(
            "seldon_eventloop_lag_seconds",
            "Serving event-loop lag EWMA (scheduled-vs-actual callback "
            "delta; a saturated loop shows here first)",
            ["service"],
            registry=self.registry,
        )
        self.host_syncs = Counter(
            "seldon_executor_host_syncs",
            "Host<->device synchronization points (result materializations) "
            "— divide by device steps for syncs/step",
            ["model_name"],
            registry=self.registry,
        )
        self.device_frac = Gauge(
            "seldon_executor_step_device_frac",
            "Fraction of the last device step spent waiting on the device "
            "(fetch) vs host-side dispatch work",
            ["model_name"],
            registry=self.registry,
        )
        # Caching & reuse plane vocabulary (fed by cache/content.py,
        # cache/singleflight.py, cache/prefix.py; docs/CACHING.md)
        self.cache_hits = Counter(
            "seldon_cache_hits",
            "Response-cache hits per tier (gateway/engine/node) and namespace",
            ["tier", "name"],
            registry=self.registry,
        )
        self.cache_misses = Counter(
            "seldon_cache_misses",
            "Response-cache misses per tier and namespace",
            ["tier", "name"],
            registry=self.registry,
        )
        self.cache_entries = Gauge(
            "seldon_cache_entries",
            "Live response-cache entries per tier",
            ["tier"],
            registry=self.registry,
        )
        self.cache_bytes = Gauge(
            "seldon_cache_bytes",
            "Bytes held by the response cache per tier",
            ["tier"],
            registry=self.registry,
        )
        self.cache_collapsed = Counter(
            "seldon_cache_collapsed",
            "Requests collapsed onto an identical in-flight computation "
            "(single-flight followers; leaders are regular requests)",
            ["name"],
            registry=self.registry,
        )
        self.prefix_tokens_reused = Counter(
            "seldon_cache_prefix_tokens_reused",
            "Prompt tokens whose prefill was skipped via KV prefix reuse",
            ["model_name"],
            registry=self.registry,
        )
        self.prefix_blocks = Gauge(
            "seldon_cache_prefix_blocks",
            "KV pool blocks currently held by the prefix-reuse index",
            ["model_name"],
            registry=self.registry,
        )
        # Tiered prefix store (docs/CACHING.md "Tiered prefix store"):
        # per-tier (hbm/dram/peer) flow counters refreshed from the tier
        # snapshots at scrape time — gauges over monotonic totals, like
        # the kv_* family.
        self.prefix_tier_hits = Gauge(
            "seldon_prefix_tier_hits",
            "Prefix matches satisfied by this tier (hbm/dram/peer)",
            ["model_name", "tier"],
            registry=self.registry,
        )
        self.prefix_tier_promotions = Gauge(
            "seldon_prefix_tier_promotions",
            "Chain levels promoted out of this tier into HBM (dram: fused "
            "promotion scatters; peer: levels installed from pulls)",
            ["model_name", "tier"],
            registry=self.registry,
        )
        self.prefix_tier_demotions = Gauge(
            "seldon_prefix_tier_demotions",
            "Chain levels demoted out of this tier (hbm: index evictions; "
            "dram levels absorbed ride the dram tier's own counter)",
            ["model_name", "tier"],
            registry=self.registry,
        )
        self.prefix_tier_bytes = Gauge(
            "seldon_prefix_tier_bytes",
            "Bytes of prefix KV currently held by this tier",
            ["model_name", "tier"],
            registry=self.registry,
        )
        # Speculative decoding (docs/PERFORMANCE.md): the acceptance ledger
        # behind accepted_tokens_per_step — emitted tokens over (slot,
        # verify-pass) pairs; > 1.0 means the n-gram drafts pay for
        # themselves on the live traffic mix.
        self.spec_emitted = Counter(
            "seldon_spec_emitted_tokens",
            "Tokens emitted by fused speculative verify passes",
            ["model_name"],
            registry=self.registry,
        )
        self.spec_verify_passes = Counter(
            "seldon_spec_verify_passes",
            "Per-slot speculative verify passes (active slot x fused step)",
            ["model_name"],
            registry=self.registry,
        )
        self.spec_accepted_per_step = Gauge(
            "seldon_spec_accepted_tokens_per_step",
            "Cumulative tokens emitted per verify pass (speculative decode "
            "acceptance; 1.0 = no draft ever accepted)",
            ["model_name"],
            registry=self.registry,
        )
        # per-proposer split of the same ledger (ngram / heads / draft):
        # the unlabeled series above stay backward-compatible; these let a
        # fleet compare proposers across deployments on one dashboard
        self.spec_emitted_by_method = Counter(
            "seldon_spec_emitted_tokens_by_method",
            "Tokens emitted by fused speculative verify passes, split by "
            "proposer (spec_method)",
            ["model_name", "spec_method"],
            registry=self.registry,
        )
        self.spec_verify_passes_by_method = Counter(
            "seldon_spec_verify_passes_by_method",
            "Per-slot speculative verify passes, split by proposer "
            "(spec_method)",
            ["model_name", "spec_method"],
            registry=self.registry,
        )
        self.spec_accepted_per_step_by_method = Gauge(
            "seldon_spec_accepted_tokens_per_step_by_method",
            "Cumulative tokens emitted per verify pass, split by proposer "
            "(spec_method)",
            ["model_name", "spec_method"],
            registry=self.registry,
        )
        # LLM graph plane (docs/GRAPHS.md): cascade routing + the semantic
        # response-cache tier
        self.cascade_requests = Counter(
            "seldon_cascade_requests",
            "Requests whose final answer came from this cascade tier "
            "(tier is the 0-based position in the ordered tier list)",
            ["name", "tier"],
            registry=self.registry,
        )
        self.cascade_escalations = Counter(
            "seldon_cascade_escalations",
            "Cascade escalations to the next tier, by reason "
            "(low-confidence)",
            ["name"],
            registry=self.registry,
        )
        self.cascade_confidence = Gauge(
            "seldon_cascade_confidence",
            "Last observed cheap-tier confidence (mean top-2 logit margin) "
            "at this cascade router",
            ["name"],
            registry=self.registry,
        )
        self.semcache_hits = Counter(
            "seldon_semcache_hits",
            "Semantic cache-tier hits (cosine >= threshold) per namespace",
            ["name"],
            registry=self.registry,
        )
        self.semcache_misses = Counter(
            "seldon_semcache_misses",
            "Semantic cache-tier misses per namespace",
            ["name"],
            registry=self.registry,
        )
        self.semcache_entries = Gauge(
            "seldon_semcache_entries",
            "Live semantic cache-tier entries",
            [],
            registry=self.registry,
        )
        self.semcache_bytes = Gauge(
            "seldon_semcache_bytes",
            "Bytes held by the semantic cache tier (vectors + responses)",
            [],
            registry=self.registry,
        )
        self.guardrail_actions = Counter(
            "seldon_guardrail_actions",
            "Guardrail-unit outcomes (action: pass / scrub / truncate / "
            "stop / block) per unit name",
            ["name", "action"],
            registry=self.registry,
        )
        self.kv_slots_per_chip = Gauge(
            "seldon_kv_slots_per_chip",
            "Max-seq sequences the paged-KV layout fits per chip after "
            "weights (int8 KV quantization ~doubles this)",
            ["model_name"],
            registry=self.registry,
        )
        # KV/HBM pool ledger (docs/OBSERVABILITY.md "generation
        # forensics"; refreshed from GenerativeModel.pool_snapshot at
        # /prometheus and /stats/breakdown time — the pressure signals the
        # router and autoscaler arbitrate on)
        self.kv_blocks = Gauge(
            "seldon_kv_blocks",
            "Paged-KV pool blocks by holder (state: free / prefix_index / "
            "slots)",
            ["model_name", "state"],
            registry=self.registry,
        )
        self.kv_blocks_high_water = Gauge(
            "seldon_kv_blocks_high_water",
            "High-water mark of paged-KV pool blocks in use since boot",
            ["model_name"],
            registry=self.registry,
        )
        self.kv_bytes = Gauge(
            "seldon_kv_bytes",
            "HBM bytes by class (weights / kv_pool / kv_scales) for one "
            "generative unit",
            ["model_name", "class"],
            registry=self.registry,
        )
        self.kv_prefix_evictions = Gauge(
            "seldon_kv_prefix_evictions",
            "Cumulative prefix-index entries evicted under pool pressure "
            "or flush",
            ["model_name"],
            registry=self.registry,
        )
        # program-cache telemetry: a mid-traffic compile (warmup gap) is a
        # counted, span-recorded event instead of a mystery latency spike
        self.program_compiles = Counter(
            "seldon_program_compiles",
            "Fresh XLA program compiles in the generative program caches "
            "(warmup + serving; serving-time ones also record a "
            "program.compile span)",
            ["model_name"],
            registry=self.registry,
        )
        # batched multi-LoRA serving (docs/MULTITENANT.md): adapter-pool
        # residency/eviction/bytes gauges refreshed at snapshot time, plus
        # a per-adapter served-token counter fed by the delivery loop
        self.lora_resident = Gauge(
            "seldon_lora_resident_adapters",
            "Named LoRA adapters resident in the stacked device pool",
            ["model_name"],
            registry=self.registry,
        )
        self.lora_evictions = Gauge(
            "seldon_lora_evictions",
            "Cumulative LRU evictions from the adapter pool",
            ["model_name"],
            registry=self.registry,
        )
        self.lora_bytes = Gauge(
            "seldon_lora_pool_bytes",
            "HBM bytes held by the stacked LoRA adapter pool (also the "
            "adapter_pool class of seldon_kv_bytes)",
            ["model_name"],
            registry=self.registry,
        )
        self.lora_tokens = Counter(
            "seldon_lora_tokens",
            "Generated tokens served per named adapter",
            ["model_name", "adapter"],
            registry=self.registry,
        )
        self.obs_spans = Gauge(
            "seldon_obs_spans",
            "Span recorder counters (state: recorded / ring / sampled_out)",
            ["state"],
            registry=self.registry,
        )
        self.obs_export = Gauge(
            "seldon_obs_span_export",
            "Span exporter totals across configured exporters "
            "(result: exported / dropped)",
            ["result"],
            registry=self.registry,
        )
        self.fleet_replicas = Gauge(
            "seldon_fleet_replicas",
            "Replicas per deployment as the fleet collector sees them "
            "(status: live / stale)",
            ["deployment", "status"],
            registry=self.registry,
        )
        self.fleet_counter = Gauge(
            "seldon_fleet_counter",
            "Fleet-summed QoS counters per deployment (admitted_total / "
            "shed_total / deadline_miss_total)",
            ["deployment", "counter"],
            registry=self.registry,
        )
        self.fleet_p99_ms = Gauge(
            "seldon_fleet_p99_ms",
            "Histogram-merged fleet p99 per flight-recorder stage (ms)",
            ["deployment", "stage"],
            registry=self.registry,
        )
        self.slo_burn_rate = Gauge(
            "seldon_slo_burn_rate",
            "SLO error-budget burn rate per objective and window "
            "(1.0 = burning exactly the budget)",
            ["deployment", "objective", "window"],
            registry=self.registry,
        )
        self.slo_state = Gauge(
            "seldon_slo_state",
            "SLO state per objective (0 ok, 1 warn, 2 page)",
            ["deployment", "objective"],
            registry=self.registry,
        )
        self.slo_transitions = Counter(
            "seldon_slo_transitions",
            "SLO state-machine transitions, labeled by the state "
            "entered",
            ["deployment", "objective", "to"],
            registry=self.registry,
        )
        self.autoscale_target = Gauge(
            "seldon_autoscale_target_replicas",
            "Latest per-pool replica target computed by the autoscale "
            "policy (docs/AUTOSCALING.md)",
            ["deployment", "role"],
            registry=self.registry,
        )
        self.autoscale_pressure = Gauge(
            "seldon_autoscale_pressure",
            "Max signal pressure (smoothed value / declared target) "
            "driving the latest decision (1.0 = at target)",
            ["deployment"],
            registry=self.registry,
        )
        self.autoscale_decisions = Counter(
            "seldon_autoscale_decisions",
            "Autoscale decisions actuated, labeled by direction "
            "(up / down) and the policy reason",
            ["deployment", "direction", "reason"],
            registry=self.registry,
        )
        self.autoscale_drains = Counter(
            "seldon_autoscale_drains",
            "Drain-based shrink outcomes (ok: victim migrated all "
            "streams; failed: shrink aborted, replica kept)",
            ["deployment", "outcome"],
            registry=self.registry,
        )
        # Per-tenant cost attribution (obs/metering.py; refreshed from the
        # UsageMeter's top-K export at /prometheus scrape time — gauges
        # over monotonic totals, like the prefix_tier/kv_* families.
        # Cardinality is bounded by construction: SCT_METER_TOP_K rows
        # plus one `other` rollup.)
        self.usage_device_seconds = Gauge(
            "seldon_usage_device_seconds",
            "Device-step seconds attributed per tenant (fused blocks "
            "split across occupied slots by token share)",
            ["deployment", "adapter", "qos"],
            registry=self.registry,
        )
        self.usage_grant_seconds = Gauge(
            "seldon_usage_grant_seconds",
            "Arbiter grant-interval wall seconds the deployment held the "
            "device",
            ["deployment", "adapter", "qos"],
            registry=self.registry,
        )
        self.usage_tokens = Gauge(
            "seldon_usage_tokens",
            "Tokens attributed per tenant by kind (prefill / decode / "
            "spec_accepted / saved_hbm / saved_dram / saved_peer / "
            "wasted)",
            ["deployment", "adapter", "qos", "kind"],
            registry=self.registry,
        )
        self.usage_requests = Gauge(
            "seldon_usage_requests",
            "Requests attributed per tenant by outcome (completed / shed "
            "/ reaped / cached)",
            ["deployment", "adapter", "qos", "outcome"],
            registry=self.registry,
        )
        self.usage_suspend_byte_seconds = Gauge(
            "seldon_usage_suspend_byte_seconds",
            "Bytes x seconds a tenant's preempted KV sat parked in the "
            "host suspend store",
            ["deployment", "adapter", "qos"],
            registry=self.registry,
        )
        self.usage_meter_keys = Gauge(
            "seldon_usage_meter_keys",
            "Live usage-meter key rows (LRU-bounded by "
            "SCT_METER_MAX_KEYS)",
            registry=self.registry,
        )
        self.usage_meter_evicted = Gauge(
            "seldon_usage_meter_evicted",
            "Key rows LRU-evicted into the `other` rollup since boot",
            registry=self.registry,
        )
        # bounded adapter->label mapping for per-adapter families
        # (seldon_lora_tokens and friends): first SCT_METER_ADAPTER_LABELS
        # distinct adapters keep their own label value, later ones report
        # as `other` so tenant churn can't grow the label set unbounded
        self._adapter_label_lock = threading.Lock()
        self._adapter_labels: dict[str, str] = {}
        self._adapter_label_max: int | None = None
        self.adapter_rollups = 0

    @contextmanager
    def time_server_request(
        self, deployment: str, predictor: str, service: str, method: str
    ):
        """Times a request; records the status code set by the caller via
        ``holder['code']``."""
        holder = {"code": "200"}
        start = time.perf_counter()
        try:
            yield holder
        finally:
            self.server_requests.labels(
                deployment, predictor, service, method, holder["code"]
            ).observe(time.perf_counter() - start)

    def record_custom(
        self, deployment: str, predictor: str, model: str, metrics
    ) -> None:
        for m in metrics:
            if m.type == "GAUGE":
                self.custom_gauge.labels(deployment, predictor, model, m.key).set(m.value)
            elif m.type == "TIMER":
                self.custom_timer.labels(deployment, predictor, model, m.key).observe(
                    m.value / 1000.0
                )
            else:
                self.custom_counter.labels(deployment, predictor, model, m.key).inc(
                    m.value
                )

    def adapter_label(self, adapter: str) -> str:
        """Bounded label value for per-adapter metric families.  The
        first ``SCT_METER_ADAPTER_LABELS`` distinct adapters keep their
        own label; every later adapter reports as ``other`` (counted in
        ``adapter_rollups``).  The null adapter passes through untouched
        — base-deployment traffic is not a rollup tenant."""
        if not adapter:
            return adapter
        with self._adapter_label_lock:
            lbl = self._adapter_labels.get(adapter)
            if lbl is not None:
                return lbl
            if self._adapter_label_max is None:
                from seldon_core_tpu.runtime import settings

                self._adapter_label_max = max(
                    0, settings.get_int("SCT_METER_ADAPTER_LABELS")
                )
            if len(self._adapter_labels) < self._adapter_label_max:
                self._adapter_labels[adapter] = adapter
                return adapter
            self.adapter_rollups += 1
            return "other"

    def refresh_usage(self, meter=None) -> None:
        """Re-derive the ``seldon_usage_*`` gauge families from the usage
        meter's bounded top-K export (called at /prometheus scrape time).
        Label sets are rebuilt from scratch each refresh so rows that
        fell out of the top-K don't linger as stale series."""
        if meter is None:
            from seldon_core_tpu.obs.metering import METER as meter
        if not meter.enabled:
            return
        rows = meter.export_rows()
        for fam in (
            self.usage_device_seconds,
            self.usage_grant_seconds,
            self.usage_tokens,
            self.usage_requests,
            self.usage_suspend_byte_seconds,
        ):
            fam.clear()
        for (dep, adapter, qos), row in rows:
            self.usage_device_seconds.labels(dep, adapter, qos).set(
                row.get("device_s", 0.0)
            )
            if "grant_s" in row:
                self.usage_grant_seconds.labels(dep, adapter, qos).set(
                    row["grant_s"]
                )
            for kind, field in _USAGE_TOKEN_KINDS:
                if field in row:
                    self.usage_tokens.labels(dep, adapter, qos, kind).set(
                        row[field]
                    )
            for outcome, field in _USAGE_REQ_OUTCOMES:
                if field in row:
                    self.usage_requests.labels(dep, adapter, qos, outcome).set(
                        row[field]
                    )
            if "suspend_byte_s" in row:
                self.usage_suspend_byte_seconds.labels(dep, adapter, qos).set(
                    row["suspend_byte_s"]
                )
        self.usage_meter_keys.set(meter.size())
        self.usage_meter_evicted.set(meter.evicted)

    def expose(self) -> bytes:
        """The /prometheus payload: classic text exposition, or
        OpenMetrics (exemplars rendered) when SCT_METRICS_EXEMPLARS is
        on — pair with :meth:`expose_content_type`."""
        if exemplars_enabled():
            from prometheus_client.openmetrics.exposition import (
                generate_latest as om_generate_latest,
            )

            return om_generate_latest(self.registry)
        return generate_latest(self.registry)

    def expose_content_type(self) -> str:
        return (
            OPENMETRICS_CONTENT_TYPE if exemplars_enabled()
            else PLAIN_CONTENT_TYPE
        )


# default process-wide registry
DEFAULT = MetricsRegistry()
