"""Executor layer tests: mesh factorization, compiled-model bucketing,
sharded placement, and the continuous-batching queue.

Run on CPU with 8 virtual XLA devices (see conftest.py) so dp/tp sharding is
exercised without TPU hardware.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seldon_core_tpu.executor import BatchQueue, BucketSpec, CompiledModel, JaxModelComponent
from seldon_core_tpu.parallel import MeshPlan, best_mesh, make_mesh
from seldon_core_tpu.parallel.sharding import DEFAULT_RULES, logical_sharding

run = asyncio.run


def linear_apply(params, x):
    return x @ params["w"] + params["b"]


def make_linear(din=4, dout=3):
    rng = np.random.default_rng(0)
    return {
        "w": rng.normal(size=(din, dout)).astype(np.float32),
        "b": np.zeros(dout, dtype=np.float32),
    }


class TestMesh:
    def test_plan_shape(self):
        assert MeshPlan(dp=2, tp=4).n_devices == 8

    def test_make_mesh_8(self):
        mesh = make_mesh(MeshPlan(dp=2, tp=4))
        assert mesh.shape == {"dp": 2, "fsdp": 1, "tp": 4, "sp": 1}

    def test_best_mesh_defaults_tp(self):
        mesh = best_mesh(8)
        assert mesh.shape["tp"] == 8 or mesh.shape["tp"] * mesh.shape["dp"] == 8

    def test_best_mesh_with_sp(self):
        mesh = best_mesh(8, tp=2, sp=2)
        assert mesh.shape == {"dp": 2, "fsdp": 1, "tp": 2, "sp": 2}

    def test_too_few_devices_raises(self):
        with pytest.raises(ValueError):
            make_mesh(MeshPlan(dp=100))

    def test_rules_spec(self):
        spec = DEFAULT_RULES.spec(("batch", "heads"))
        assert spec == jax.sharding.PartitionSpec(("dp", "fsdp"), "tp")


class TestCompiledModel:
    def test_exact_result_and_bucketing(self):
        params = make_linear()
        m = CompiledModel(linear_apply, params, buckets=BucketSpec((2, 4, 8)))
        x = np.random.default_rng(1).normal(size=(3, 4)).astype(np.float32)
        np.testing.assert_allclose(m(x), x @ params["w"] + params["b"], rtol=1e-5)
        assert m(x).shape == (3, 3)  # padding sliced off

    def test_single_row_squeeze(self):
        m = CompiledModel(linear_apply, make_linear())
        out = m(np.ones(4, dtype=np.float32))
        assert out.shape == (3,)

    def test_oversize_batch_chunks(self):
        m = CompiledModel(linear_apply, make_linear(), buckets=BucketSpec((2, 4)))
        x = np.ones((11, 4), dtype=np.float32)
        assert m(x).shape == (11, 3)

    def test_sharded_over_mesh(self):
        mesh = best_mesh(8, tp=2)
        params = make_linear(8, 6)
        m = CompiledModel(
            linear_apply,
            params,
            mesh=mesh,
            param_axes={"w": ("hidden", "mlp"), "b": ("mlp",)},
            buckets=BucketSpec((8,)),
        )
        x = np.random.default_rng(2).normal(size=(8, 8)).astype(np.float32)
        np.testing.assert_allclose(m(x), x @ params["w"] + params["b"], rtol=1e-4)
        # params really are sharded along tp
        w_sharding = m.params["w"].sharding
        assert w_sharding.spec == jax.sharding.PartitionSpec(None, "tp")

    def test_sharded_buckets_round_to_shard_multiple(self):
        """dp>1 meshes must not offer bucket sizes the batch axis can't shard."""
        mesh = best_mesh(8, tp=2)  # dp=4
        m = CompiledModel(linear_apply, make_linear(), mesh=mesh)
        assert all(s % 4 == 0 for s in m.buckets.sizes)
        out = m(np.ones((1, 4), dtype=np.float32))  # 1 row pads to 4
        assert out.shape == (3,) or out.shape == (1, 3)
        assert m.warmup((4,)) == len(m.buckets.sizes)

    def test_bfloat16_cast(self):
        m = CompiledModel(linear_apply, make_linear(), dtype=jnp.bfloat16)
        assert m.params["w"].dtype == jnp.bfloat16

    def test_warmup_compiles_all_buckets(self):
        m = CompiledModel(linear_apply, make_linear(), buckets=BucketSpec((1, 2)))
        assert m.warmup((4,)) == 2

    def test_aot_lower(self):
        m = CompiledModel(linear_apply, make_linear(), buckets=BucketSpec((4,)))
        lowered = m.aot_lower((4,))
        assert "4,4" in lowered.as_text() or lowered is not None


class TestBatchQueue:
    def test_concurrent_submits_coalesce(self):
        params = make_linear()
        m = CompiledModel(linear_apply, params, buckets=BucketSpec((1, 2, 4, 8, 16, 32)))

        async def go():
            q = BatchQueue(m, max_batch=32, max_delay_ms=20.0)
            xs = [np.random.default_rng(i).normal(size=(1, 4)).astype(np.float32) for i in range(16)]
            outs = await asyncio.gather(*(q.submit(x) for x in xs))
            await q.close()
            return xs, outs, q.steps

        xs, outs, steps = run(go())
        for x, out in zip(xs, outs):
            np.testing.assert_allclose(out, x @ params["w"] + params["b"], rtol=1e-5)
        assert steps < 16  # actually batched, not one step per request

    def test_mixed_shapes_dont_mix(self):
        async def go():
            q = BatchQueue(lambda b: b * 2.0, max_batch=8, max_delay_ms=5.0)
            a = q.submit(np.ones((1, 3), dtype=np.float32))
            b = q.submit(np.ones((1, 5), dtype=np.float32))
            ra, rb = await asyncio.gather(a, b)
            await q.close()
            return ra, rb

        ra, rb = run(go())
        assert ra.shape == (1, 3) and rb.shape == (1, 5)

    def test_close_fails_pending_requests(self):
        """Drain must error queued requests, not hang their awaiters."""

        async def go():
            q = BatchQueue(lambda b: b, max_batch=4, max_delay_ms=50.0)
            t1 = asyncio.ensure_future(q.submit(np.ones((1, 2), dtype=np.float32)))
            await asyncio.sleep(0.005)  # let the loop start collecting
            await q.close()
            with pytest.raises(RuntimeError):
                await t1

        run(go())

    def test_minority_shape_not_starved(self):
        """A misfit held over during collection seeds the next group."""
        seen = []

        def runner(b):
            seen.append(b.shape)
            return b

        async def go():
            q = BatchQueue(runner, max_batch=64, max_delay_ms=10.0)
            maj = [q.submit(np.ones((1, 3), dtype=np.float32)) for _ in range(6)]
            mino = q.submit(np.ones((1, 5), dtype=np.float32))
            await asyncio.wait_for(asyncio.gather(*maj, mino), timeout=5.0)
            await q.close()

        run(go())
        assert (1, 5) in [s[:1] + s[1:] for s in seen] or any(s[1] == 5 for s in seen)

    def test_runner_error_propagates(self):
        def bad(_):
            raise ValueError("boom")

        async def go():
            q = BatchQueue(bad, max_delay_ms=1.0)
            with pytest.raises(ValueError):
                await q.submit(np.ones((1, 2)))
            await q.close()

        run(go())


class TestJaxModelComponent:
    def test_acts_as_graph_unit(self):
        params = make_linear()
        m = CompiledModel(linear_apply, params, name="lin")
        comp = JaxModelComponent(m, class_names=["a", "b", "c"])

        async def go():
            out = await comp.predict(np.ones((2, 4), dtype=np.float32), [])
            await comp.close()
            return out

        out = run(go())
        assert out.shape == (2, 3)
        assert comp.class_names == ["a", "b", "c"]


class TestCheckpointSkeletonStrictness:
    """JSON skeletons cannot represent non-string dict keys or namedtuple
    classes; silently coercing them corrupts the tree at load time — the
    save must fail loudly instead."""

    def test_int_dict_keys_rejected_at_save(self, tmp_path):
        import pytest as _pytest

        from seldon_core_tpu.executor.checkpoint import save_params

        with _pytest.raises(TypeError, match="keys must be str"):
            save_params(str(tmp_path / "c.npz"), {0: np.zeros(2), 1: np.ones(2)})

    def test_namedtuple_rejected_at_save(self, tmp_path):
        import collections

        import pytest as _pytest

        from seldon_core_tpu.executor.checkpoint import save_params

        PT = collections.namedtuple("PT", ["w"])
        with _pytest.raises(TypeError, match="namedtuple"):
            save_params(str(tmp_path / "c.npz"), PT(w=np.zeros(2)))
