# Iris scorer in R — served by the wrappers/r runtime (plumber).
# Hand-fitted linear scores, softmax over 3 classes; mirrors
# examples/iris/IrisClassifier.py so the two runtimes are comparable.

W <- matrix(c(
   0.4,  1.3, -2.0, -0.9,
   0.3, -0.5,  0.1, -0.8,
  -0.7, -1.2,  2.1,  2.2
), nrow = 3, byrow = TRUE)
b <- c(0.8, 1.5, -2.3)

names_out <- c("setosa", "versicolor", "virginica")

predict_model <- function(X) {
  scores <- X %*% t(W) + matrix(b, nrow(X), 3, byrow = TRUE)
  e <- exp(scores - apply(scores, 1, max))
  e / rowSums(e)
}
