"""Version-guarded stdlib/toolchain shims.

Tier-1 runs on the floor interpreter (3.10) while production images track
newer runtimes; anything that needs an API that moved between versions
goes through here so call sites stay clean and the guard lives in ONE
place.
"""

from __future__ import annotations

import asyncio
import contextvars
import sys
from typing import Any, Coroutine


def create_task_in_context(
    loop: asyncio.AbstractEventLoop,
    coro: Coroutine[Any, Any, Any],
    ctx: contextvars.Context,
) -> asyncio.Task:
    """``loop.create_task(coro, context=ctx)`` with a 3.10 fallback.

    The ``context=`` kwarg landed in 3.11.  On 3.10 a Task snapshots the
    context ACTIVE at creation (``contextvars.copy_context()``), so
    creating the task from inside ``ctx.run`` pins the same context the
    kwarg would — the handler runs with ``ctx``'s values and writes never
    leak into the caller's context.
    """
    if sys.version_info >= (3, 11):
        return loop.create_task(coro, context=ctx)
    return ctx.run(loop.create_task, coro)
