"""Operator tests: defaulting/validation (mirrors the reference's
SeldonDeploymentDefaultingTest/ValidationTest), resource generation, and the
full reconcile loop against the in-process fake k8s API — including orphan
GC, FAILED parking, status writeback, and the watch loop."""

import asyncio
import base64
import json

import pytest

from seldon_core_tpu.operator.controller import CR_KIND, Controller
from seldon_core_tpu.operator.crd import SeldonDeployment
from seldon_core_tpu.operator.defaulting import ValidationError, defaulting, validate
from seldon_core_tpu.operator.kube import FakeKube, NotFound
from seldon_core_tpu.operator.resources import create_resources
from seldon_core_tpu.operator.watcher import OperatorLoop

run = asyncio.run


def mk_cr(name="mydep", graph=None, containers=("classifier",), replicas=1):
    graph = graph or {"name": "classifier", "type": "MODEL"}
    return SeldonDeployment.from_dict(
        {
            "metadata": {"name": name, "namespace": "default"},
            "spec": {
                "name": name,
                "oauth_key": "k",
                "oauth_secret": "s",
                "predictors": [
                    {
                        "name": "p1",
                        "replicas": replicas,
                        "graph": graph,
                        "componentSpecs": [
                            {
                                "spec": {
                                    "containers": [
                                        {"name": c, "image": f"user/{c}:1"}
                                        for c in containers
                                    ]
                                }
                            }
                        ],
                    }
                ],
            },
        }
    )


class TestDefaulting:
    def test_ports_env_endpoint(self):
        out = defaulting(mk_cr())
        pred = out.spec.predictors[0]
        c = pred.componentSpecs[0]["spec"]["containers"][0]
        env = {e["name"]: e["value"] for e in c["env"]}
        assert env["PREDICTIVE_UNIT_SERVICE_PORT"] == "9000"
        assert env["PREDICTIVE_UNIT_ID"] == "classifier"
        assert env["PREDICTOR_ID"] == "p1" and env["SELDON_DEPLOYMENT_ID"] == "mydep"
        assert c["readinessProbe"]["tcpSocket"]["port"] == 9000
        unit = pred.graph
        assert unit.endpoint.service_host == "mydep-p1-classifier"
        assert unit.endpoint.service_port == 9000
        assert unit.endpoint.type.value == "REST"

    def test_distinct_containers_distinct_ports(self):
        cr = mk_cr(
            graph={
                "name": "a",
                "type": "MODEL",
                "children": [{"name": "b", "type": "MODEL"}],
            },
            containers=("a", "b"),
        )
        out = defaulting(cr)
        env_by = {}
        for c in out.spec.predictors[0].componentSpecs[0]["spec"]["containers"]:
            env_by[c["name"]] = {e["name"]: e["value"] for e in c["env"]}
        assert env_by["a"]["PREDICTIVE_UNIT_SERVICE_PORT"] == "9000"
        assert env_by["b"]["PREDICTIVE_UNIT_SERVICE_PORT"] == "9001"

    def test_builtin_unit_keeps_local_endpoint(self):
        cr = mk_cr(graph={"name": "sm", "type": "MODEL", "implementation": "SIMPLE_MODEL"})
        out = defaulting(cr)
        assert out.spec.predictors[0].graph.endpoint.type.value == "LOCAL"

    def test_tpu_node_selector(self):
        cr = mk_cr()
        cr.spec.annotations["seldon.io/tpu-accelerator"] = "tpu-v5-lite-podslice"
        cr.spec.predictors[0].componentSpecs[0]["spec"]["containers"][0]["resources"] = {
            "limits": {"google.com/tpu": "8"}
        }
        out = defaulting(cr)
        pod_spec = out.spec.predictors[0].componentSpecs[0]["spec"]
        assert pod_spec["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"] == (
            "tpu-v5-lite-podslice"
        )

    def test_input_not_mutated(self):
        cr = mk_cr()
        defaulting(cr)
        c = cr.spec.predictors[0].componentSpecs[0]["spec"]["containers"][0]
        assert "env" not in c


class TestValidation:
    def test_valid_after_defaulting(self):
        validate(defaulting(mk_cr()))

    def test_model_without_container_or_impl_rejected(self):
        cr = mk_cr(graph={"name": "ghost", "type": "MODEL"}, containers=("other",))
        with pytest.raises(ValidationError):
            validate(defaulting(cr))

    def test_unit_without_anything_rejected(self):
        cr = mk_cr(graph={"name": "x"})
        with pytest.raises(ValidationError):
            validate(defaulting(cr))

    def test_no_predictors_rejected(self):
        cr = mk_cr()
        cr.spec.predictors = []
        with pytest.raises(ValidationError):
            validate(cr)


class TestResources:
    def test_engine_deployment_and_services(self):
        out = defaulting(mk_cr())
        deployments, services = create_resources(out)
        names = {d["metadata"]["name"] for d in deployments}
        assert names == {"mydep-p1-engine", "mydep-p1-0"}
        svc_names = {s["metadata"]["name"] for s in services}
        assert svc_names == {"mydep-p1-classifier", "mydep"}
        # engine env round-trips to the engine's PredictorSpec loader
        engine = next(d for d in deployments if "engine" in d["metadata"]["name"])
        env = {
            e["name"]: e["value"]
            for e in engine["spec"]["template"]["spec"]["containers"][0]["env"]
        }
        decoded = json.loads(base64.b64decode(env["ENGINE_PREDICTOR"]))
        assert decoded["graph"]["endpoint"]["service_host"] == "mydep-p1-classifier"

    def test_long_names_hashed(self):
        cr = mk_cr(name="x" * 80)
        out = defaulting(cr)
        deployments, services = create_resources(out)
        for obj in deployments + services:
            assert len(obj["metadata"]["name"]) <= 63


class TestController:
    def test_create_update_orphan_gc(self):
        async def go():
            kube = FakeKube()
            ctl = Controller(kube)
            cr = mk_cr()
            await kube.create(CR_KIND, "default", cr.to_dict())
            await ctl.reconcile(cr)
            created = kube.object_names("Deployment")
            # change the graph: drop the container-based model for a builtin
            cr2 = mk_cr(graph={"name": "sm", "type": "MODEL", "implementation": "SIMPLE_MODEL"})
            cr2.spec.predictors[0].componentSpecs = []
            await ctl.reconcile(cr2)
            after = kube.object_names("Deployment")
            svcs = kube.object_names("Service")
            return created, after, svcs

        created, after, svcs = run(go())
        assert created == {"mydep-p1-engine", "mydep-p1-0"}
        assert after == {"mydep-p1-engine"}  # component deployment GC'd
        assert svcs == {"mydep"}  # per-container service GC'd

    def test_failed_parking_until_spec_changes(self):
        async def go():
            kube = FakeKube()
            ctl = Controller(kube)
            bad = mk_cr(graph={"name": "ghost", "type": "MODEL"}, containers=("other",))
            await kube.create(CR_KIND, "default", bad.to_dict())
            await ctl.reconcile(bad)
            st1 = (await kube.get(CR_KIND, "default", "mydep")).get("status", {})
            await ctl.reconcile(bad)  # parked: no further work, still FAILED
            good = mk_cr()
            await ctl.reconcile(good)
            st2 = (await kube.get(CR_KIND, "default", "mydep")).get("status", {})
            return st1, st2, kube.object_names("Deployment")

        st1, st2, deps = run(go())
        assert st1["state"] == "FAILED"
        assert st2["state"] in ("Creating", "Available")
        assert "mydep-p1-engine" in deps

    def test_status_writeback_on_replica_progress(self):
        async def go():
            kube = FakeKube()
            ctl = Controller(kube)
            cr = mk_cr()
            await kube.create(CR_KIND, "default", cr.to_dict())
            await ctl.reconcile(cr)
            st0 = (await kube.get(CR_KIND, "default", "mydep"))["status"]
            kube.set_available_replicas("default", "mydep-p1-engine", 1)
            eng = await kube.get("Deployment", "default", "mydep-p1-engine")
            await ctl.on_deployment_event(eng)
            st1 = (await kube.get(CR_KIND, "default", "mydep"))["status"]
            return st0, st1

        st0, st1 = run(go())
        assert st0["state"] == "Creating"
        assert st1["state"] == "Available"
        assert st1["predictorStatus"][0]["replicasAvailable"] == 1

    def test_delete_removes_owned_objects(self):
        async def go():
            kube = FakeKube()
            ctl = Controller(kube)
            cr = mk_cr()
            await kube.create(CR_KIND, "default", cr.to_dict())
            await ctl.reconcile(cr)
            await ctl.delete(cr)
            return kube.object_names("Deployment"), kube.object_names("Service")

        deps, svcs = run(go())
        assert deps == set() and svcs == set()


class TestReviewRegressions:
    def test_sidecar_containers_untouched(self):
        """Containers that are not graph units get no port/env/probe and no
        Service (a log-shipper sidecar must not be probed on a dead port)."""
        cr = mk_cr(containers=("classifier", "log-shipper"))
        out = defaulting(cr)
        containers = out.spec.predictors[0].componentSpecs[0]["spec"]["containers"]
        sidecar = next(c for c in containers if c["name"] == "log-shipper")
        assert "env" not in sidecar and "readinessProbe" not in sidecar
        _, services = create_resources(out)
        assert {s["metadata"]["name"] for s in services} == {"mydep-p1-classifier", "mydep"}

    def test_service_selector_unique_per_deployment(self):
        """Same container name in two deployments must not cross-match."""
        a = create_resources(defaulting(mk_cr(name="depa")))
        b = create_resources(defaulting(mk_cr(name="depb")))
        sa = next(s for s in a[1] if "classifier" in s["metadata"]["name"])
        sb = next(s for s in b[1] if "classifier" in s["metadata"]["name"])
        assert sa["spec"]["selector"] != sb["spec"]["selector"]

    def test_owner_references_set(self):
        async def go():
            kube = FakeKube()
            ctl = Controller(kube)
            created = await kube.create(CR_KIND, "default", mk_cr().to_dict())
            await ctl.reconcile(SeldonDeployment.from_dict(created))
            eng = await kube.get("Deployment", "default", "mydep-p1-engine")
            return eng["metadata"].get("ownerReferences", [])

        refs = run(go())
        assert refs and refs[0]["kind"] == "SeldonDeployment" and refs[0]["uid"]

    def test_transient_error_retries_not_parked(self):
        class FlakyKube(FakeKube):
            def __init__(self):
                super().__init__()
                self.fail_once = True

            async def create(self, kind, namespace, obj):
                if self.fail_once and kind == "Deployment":
                    self.fail_once = False
                    raise RuntimeError("api server hiccup")
                return await super().create(kind, namespace, obj)

        async def go():
            kube = FlakyKube()
            ctl = Controller(kube)
            cr = mk_cr()
            await kube.create(CR_KIND, "default", cr.to_dict())
            await ctl.reconcile(cr)
            st1 = (await kube.get(CR_KIND, "default", "mydep")).get("status", {})
            await ctl.reconcile(cr)  # same spec retries (not parked)
            return st1, kube.object_names("Deployment")

        st1, deps = run(go())
        assert st1["state"] == "Creating" and "retrying" in st1["description"]
        assert "mydep-p1-engine" in deps

    def test_sweep_orphans_after_missed_delete(self):
        async def go():
            kube = FakeKube()
            ctl = Controller(kube)
            cr = mk_cr()
            await kube.create(CR_KIND, "default", cr.to_dict())
            await ctl.reconcile(cr)
            # CR vanishes while "operator is down" (no DELETED dispatch)
            await kube.delete(CR_KIND, "default", "mydep")
            removed = await ctl.sweep_orphans("default")
            return removed, kube.object_names("Deployment"), kube.object_names("Service")

        removed, deps, svcs = run(go())
        # engine + component Deployments, per-container + deployment Services
        assert removed == 4 and deps == set() and svcs == set()

    def test_engine_probes_on_rest_port(self):
        deployments, _ = create_resources(defaulting(mk_cr()))
        engine = next(d for d in deployments if "engine" in d["metadata"]["name"])
        c = engine["spec"]["template"]["spec"]["containers"][0]
        assert c["readinessProbe"]["httpGet"]["port"] == 8000
        env = {e["name"]: e["value"] for e in c["env"]}
        assert env["SELDON_DEPLOYMENT_ID"] == "mydep"


class TestOperatorLoop:
    def test_watch_reconciles_new_cr(self):
        async def go():
            kube = FakeKube()
            ctl = Controller(kube)
            op = OperatorLoop(kube, ctl)
            await op.start()
            await asyncio.sleep(0.05)
            await kube.create(CR_KIND, "default", mk_cr().to_dict())
            for _ in range(100):
                await asyncio.sleep(0.01)
                if "mydep-p1-engine" in kube.object_names("Deployment"):
                    break
            names = kube.object_names("Deployment")
            await op.stop()
            return names

        names = run(go())
        assert "mydep-p1-engine" in names

    def test_watch_handles_delete(self):
        async def go():
            kube = FakeKube()
            ctl = Controller(kube)
            op = OperatorLoop(kube, ctl)
            await op.start()
            await asyncio.sleep(0.05)
            await kube.create(CR_KIND, "default", mk_cr().to_dict())
            for _ in range(100):
                await asyncio.sleep(0.01)
                if "mydep-p1-engine" in kube.object_names("Deployment"):
                    break
            await kube.delete(CR_KIND, "default", "mydep")
            for _ in range(100):
                await asyncio.sleep(0.01)
                if not kube.object_names("Deployment"):
                    break
            names = kube.object_names("Deployment")
            await op.stop()
            return names

        assert run(go()) == set()
