"""In-process span recorder + per-stage latency flight recorder.

The reference system had no distributed tracing at all (correlation was a
puid plus latency log lines); this module is the always-on Dapper-style
layer for the TPU serving hot path, with no OTel SDK dependency:

* **spans** — every hop (gateway relay, engine route, graph node) opens a
  span against the request's W3C trace context (``utils/tracectx.py``);
  finished spans land in a bounded in-process ring buffer and fan out to
  exporters (``obs/export.py``: OTLP/HTTP JSON, taplog topic).  A sampling
  knob (``SCT_TRACE_SAMPLE``, default 1.0) thins span RECORDING; context
  PROPAGATION is never sampled away, so downstream hops always correlate.
* **stages** — the flight recorder: fixed-vocabulary per-stage duration
  rings (gateway-relay / engine-route / node / queue-wait / batch-assembly
  / device-step / stream-flush / ttft) that answer "where did the p99 go"
  without reconstructing traces.  Stage recording is unconditional and
  cheap (one deque append), including from executor threads.

Both are served by ``GET /stats/spans`` and ``GET /stats/breakdown`` on the
engine and the gateway.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import os
import random
import threading
import time
from collections import defaultdict, deque
from typing import Any, Iterator

from seldon_core_tpu.obs import history as _history
from seldon_core_tpu.utils.tracectx import (
    get_traceparent,
    make_span_id,
    new_traceparent,
    parse_traceparent,
    _traceparent,
)

# the flight recorder's stage vocabulary (docs/OBSERVABILITY.md)
STAGE_GATEWAY_RELAY = "gateway-relay"
STAGE_ENGINE_ROUTE = "engine-route"
STAGE_NODE = "node"
STAGE_QUEUE_WAIT = "queue-wait"
STAGE_BATCH_ASSEMBLY = "batch-assembly"
STAGE_DEVICE_STEP = "device-step"
STAGE_DEVICE_DISPATCH = "device-dispatch"
STAGE_STREAM_FLUSH = "stream-flush"
STAGE_TTFT = "ttft"

STAGES = (
    STAGE_GATEWAY_RELAY,
    STAGE_ENGINE_ROUTE,
    STAGE_NODE,
    STAGE_QUEUE_WAIT,
    STAGE_BATCH_ASSEMBLY,
    STAGE_DEVICE_STEP,
    STAGE_DEVICE_DISPATCH,
    STAGE_STREAM_FLUSH,
    STAGE_TTFT,
)


@dataclasses.dataclass
class Span:
    """One finished span.  Times are epoch seconds (floats); exporters
    convert to OTLP nanos."""

    trace_id: str
    span_id: str
    parent_id: str | None
    name: str
    service: str
    start: float
    duration_s: float
    status: str = "OK"
    attrs: dict[str, Any] = dataclasses.field(default_factory=dict)
    events: list = dataclasses.field(default_factory=list)  # (name, epoch_s, attrs)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "service": self.service,
            "start": self.start,
            "duration_ms": round(self.duration_s * 1e3, 3),
            "status": self.status,
            "attrs": self.attrs,
            "events": [
                {"name": n, "ts": ts, "attrs": a} for n, ts, a in self.events
            ],
        }


class _LiveSpan:
    """The in-flight handle yielded by :meth:`SpanRecorder.span`."""

    __slots__ = ("span", "_t0")

    def __init__(self, span: Span, t0: float):
        self.span = span
        self._t0 = t0

    def set_attr(self, key: str, value: Any) -> None:
        self.span.attrs[key] = value

    def event(self, name: str, **attrs: Any) -> None:
        self.span.events.append((name, time.time(), attrs))

    def set_status(self, status: str) -> None:
        self.span.status = status


# the innermost live span of this async context (None when unsampled or no
# span is open) — lets deeper layers (batcher submit) attach events without
# plumbing a handle through every signature
_live_span: contextvars.ContextVar["_LiveSpan | None"] = contextvars.ContextVar(
    "sct_live_span", default=None
)


def current_span() -> "_LiveSpan | None":
    return _live_span.get()


# ``engine.role`` resource attribute (docs/OBSERVABILITY.md "cross-pool
# stitching"): every recorded span names the pool role that recorded it
# (prefill / decode / unified / gateway), so a stitched disagg trace read
# from either engine's /stats/spans attributes each hop to its pool.  A
# request-scoped contextvar (seeded at every ingress) wins over the
# process-level default (seeded at boot) — test harnesses run several
# role-typed engines in one process.
_engine_role: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "sct_engine_role", default=None
)
_process_role: str | None = None


def set_engine_role(role: str | None) -> None:
    """Seed this request context's ``engine.role`` span attribute."""
    _engine_role.set(role or None)


def set_process_role(role: str | None) -> None:
    """Process-level fallback role (engine boot) for spans recorded
    outside any request context."""
    global _process_role
    _process_role = role or None


def current_engine_role() -> str | None:
    return _engine_role.get() or _process_role


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class SpanRecorder:
    """Bounded always-on recorder; one per process (module-level RECORDER).

    Memory is bounded by construction: the span ring (``SCT_SPANS_RING``,
    default 2048 spans) and the per-stage duration rings
    (``SCT_STAGE_RING``, default 8192 samples per stage) are deques with
    maxlen — a traffic burst evicts oldest, never grows.  Exporters hang off
    :meth:`record` behind their own bounded queues (obs/export.py), so a
    dead collector or broker can only ever drop spans, never block serving.
    """

    def __init__(
        self,
        max_spans: int | None = None,
        max_stage_samples: int | None = None,
        sample: float | None = None,
    ):
        if max_spans is None:
            max_spans = int(os.environ.get("SCT_SPANS_RING", "2048"))
        if max_stage_samples is None:
            max_stage_samples = int(os.environ.get("SCT_STAGE_RING", "8192"))
        if sample is None:
            sample = float(os.environ.get("SCT_TRACE_SAMPLE", "1.0"))
        self.sample = min(1.0, max(0.0, sample))
        self._spans: deque[Span] = deque(maxlen=max_spans)
        self._stages: dict[str, deque[float]] = defaultdict(
            lambda: deque(maxlen=max_stage_samples)
        )
        for s in STAGES:  # pre-create: thread-safe appends need no __missing__
            self._stages[s]
        # cumulative (survive ring eviction); lock-free int adds are fine
        # for stats — a lost increment under a rare thread race is noise
        self._stage_counts: dict[str, int] = defaultdict(int)
        # cumulative per-stage bucket counts on the SHARED grid
        # (obs/history.BUCKET_EDGES): unlike breakdown()'s ring quantiles
        # these merge across replicas — the fleet collector sums them and
        # derives p50/p99 from the merged counts
        self._stage_hist: dict[str, list[int]] = defaultdict(_history.new_hist)
        self.recorded = 0
        self.sampled_out = 0
        self.exporters: list = []

    # -- recording ---------------------------------------------------------

    def should_sample(self) -> bool:
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        return random.random() < self.sample

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        service: str = "",
        stage: str | None = None,
        attrs: dict | None = None,
    ) -> Iterator["_LiveSpan | None"]:
        """Open a span in this async context.

        Joins the current traceparent as a child (minting a root when none
        is set), and re-points the context's span-id at this span so
        downstream hops and child spans parent correctly.  Yields the live
        span (None when sampled out — stage timing still recorded).
        An exception inside marks the span ERROR and re-raises.
        """
        tp = get_traceparent()
        parsed = parse_traceparent(tp)
        t0 = time.perf_counter()
        start = time.time()
        minted_root = parsed is None
        if minted_root:
            tp = new_traceparent(sampled=self.should_sample())
            parsed = parse_traceparent(tp)
            parent_id = None
        else:
            parent_id = parsed[1]
        trace_id, _, flags = parsed
        recording = bool(flags & 0x01) and self.sample > 0.0
        live: _LiveSpan | None = None
        live_token = None
        if recording:
            span_id = make_span_id()
            token = _traceparent.set(f"00-{trace_id}-{span_id}-{flags:02x}")
            span_attrs = dict(attrs) if attrs else {}
            role = current_engine_role()
            if role is not None:
                span_attrs.setdefault("engine.role", role)
            live = _LiveSpan(
                Span(
                    trace_id=trace_id,
                    span_id=span_id,
                    parent_id=parent_id,
                    name=name,
                    service=service,
                    start=start,
                    duration_s=0.0,
                    attrs=span_attrs,
                ),
                t0,
            )
            live_token = _live_span.set(live)
        else:
            # propagate unchanged: the decision not to RECORD must not
            # break correlation for hops that do
            token = _traceparent.set(tp)
        try:
            yield live
        except BaseException:
            if live is not None:
                live.span.status = "ERROR"
            raise
        finally:
            dt = time.perf_counter() - t0
            if not minted_root:
                # restore the parent context for sibling spans.  A minted
                # root stays set instead: the ingress layer reads it after
                # the span closes to echo the trace id, and every entry
                # point re-seeds the contextvar per request
                _traceparent.reset(token)
            if live_token is not None:
                _live_span.reset(live_token)
            if stage is not None:
                self.record_stage(stage, dt)
            if live is not None:
                live.span.duration_s = dt
                self.record(live.span)

    def record(self, span: Span) -> None:
        self._spans.append(span)
        self.recorded += 1
        for exp in self.exporters:
            exp.offer(span)

    def record_span(
        self,
        name: str,
        *,
        trace_id: str,
        parent_id: str | None,
        start: float,
        duration_s: float,
        service: str = "",
        status: str = "OK",
        attrs: dict | None = None,
        sampled: bool = True,
        span_id: str | None = None,
    ) -> None:
        """Record a span built outside a contextvar scope (protocol
        callbacks like the h1 splice and the gRPC relay time requests
        across event-loop callbacks, not within one task)."""
        if not sampled or self.sample <= 0.0:
            self.sampled_out += 1
            return
        span_attrs = dict(attrs) if attrs else {}
        role = current_engine_role()
        if role is not None:
            span_attrs.setdefault("engine.role", role)
        self.record(
            Span(
                trace_id=trace_id,
                span_id=span_id or make_span_id(),
                parent_id=parent_id,
                name=name,
                service=service,
                start=start,
                duration_s=duration_s,
                status=status,
                attrs=span_attrs,
            )
        )

    def record_stage(self, stage: str, duration_s: float) -> None:
        """Flight-recorder append: unconditional, thread-safe (deque
        append is atomic), O(1)."""
        self._stages[stage].append(duration_s)
        self._stage_counts[stage] += 1
        _history.record_hist(self._stage_hist[stage], duration_s)

    # -- reading -----------------------------------------------------------

    def stage_ewma(self, stage: str, n: int = 64, alpha: float = 0.2) -> float | None:
        """EWMA over the stage ring's last ``n`` samples (None when the
        stage has no data yet).  Feeds the QoS plane's time-to-completion
        estimate at admission (qos/admission.py) — recent samples dominate
        so the estimate tracks load shifts within a few steps."""
        ring = self._stages.get(stage)
        if not ring:
            return None
        vals = list(ring)[-max(1, n):]
        est = vals[0]
        for v in vals[1:]:
            est = alpha * v + (1.0 - alpha) * est
        return est

    def breakdown(self) -> dict:
        """Aggregated per-stage latency over the ring window:
        ``{stage: {count, window, total_ms, p50_ms, p90_ms, p99_ms,
        max_ms}}``.  ``count`` is cumulative; the quantiles and total are
        over the last ``SCT_STAGE_RING`` samples."""
        out: dict[str, dict] = {}
        for stage, ring in list(self._stages.items()):
            vals = sorted(ring)
            if not vals:
                continue
            out[stage] = {
                "count": self._stage_counts[stage],
                "window": len(vals),
                "total_ms": round(sum(vals) * 1e3, 3),
                "p50_ms": round(_percentile(vals, 0.50) * 1e3, 3),
                "p90_ms": round(_percentile(vals, 0.90) * 1e3, 3),
                "p99_ms": round(_percentile(vals, 0.99) * 1e3, 3),
                "max_ms": round(vals[-1] * 1e3, 3),
            }
        return out

    def stage_histograms(self) -> dict:
        """Cumulative per-stage bucket counts over the shared log grid
        (``obs/history.BUCKET_EDGES``) — the MERGEABLE form of
        :meth:`breakdown`.  Served in ``GET /stats/summary`` so the fleet
        collector can sum counts across replicas and compute true fleet
        percentiles instead of averaging per-replica quantiles."""
        return {
            stage: list(h)
            for stage, h in list(self._stage_hist.items())
            if self._stage_counts.get(stage)
        }

    def recent_traces(self, n: int = 20) -> list[dict]:
        """The last ``n`` traces (newest first), each with its spans in
        recording order."""
        by_trace: dict[str, list[Span]] = {}
        order: list[str] = []
        for span in self._spans:
            if span.trace_id not in by_trace:
                by_trace[span.trace_id] = []
                order.append(span.trace_id)
            by_trace[span.trace_id].append(span)
        out = []
        for tid in reversed(order[-n:]):
            spans = by_trace[tid]
            out.append(
                {
                    "trace_id": tid,
                    "span_count": len(spans),
                    "duration_ms": round(
                        max(s.duration_s for s in spans) * 1e3, 3
                    ),
                    "spans": [s.to_dict() for s in spans],
                }
            )
        return out

    def slowest(self, n: int = 10) -> list[dict]:
        """Slowest-N root spans in the ring (the tail-latency suspects)."""
        roots = [s for s in self._spans if s.parent_id is None]
        roots.sort(key=lambda s: s.duration_s, reverse=True)
        return [s.to_dict() for s in roots[:n]]

    def stats(self, n: int = 20) -> dict:
        """The ``GET /stats/spans`` payload."""
        export = {}
        for exp in self.exporters:
            export[type(exp).__name__] = {
                "exported": exp.exported,
                "dropped": exp.dropped,
            }
        return {
            "recorded": self.recorded,
            "ring": len(self._spans),
            "sample": self.sample,
            "exporters": export,
            "slowest": self.slowest(min(n, 10)),
            "traces": self.recent_traces(n),
        }


# default process-wide recorder (mirrors utils/metrics.DEFAULT)
RECORDER = SpanRecorder()
