"""Chunked prefill + Pallas paged decode-attention gates
(docs/PERFORMANCE.md §7), CPU-safe:

* **pinned-equal chunking** — generation with chunked prefill ON is
  bit-identical to the monolithic prefill: greedy and seeded top-k, with
  KV prefix reuse (chunking applies to the novel suffix only), under int8
  paged KV, on a tp=2 sharded mesh, and across a disagg handoff of a
  chunk-prefilled slot;
* **stall-free interleave** — admissions arriving while streams decode are
  paced one chunk per sync point (the Sarathi property), the greedy stream
  stays bit-identical, and the host-sync audit stays <= 1 sync per fused
  block;
* **ITL ledger** — per-slot inter-token latency lands in
  ``spec_snapshot()`` (``itl_p50_ms``/``itl_p99_ms``, the
  ``/stats/breakdown`` generation section) and the ``seldon_itl_seconds``
  histogram;
* **kernel pinned-equal** — generation with the Pallas decode kernel ON
  matches the XLA gather path bit-for-bit in interpret mode (float and
  int8 pools); direct kernel-vs-reference equality lives in test_ops.py;
* **program cache-key audit** — ``prefill_chunk`` and ``decode_kernel``
  are folded into every compiled-program cache key, and ``/stats/warmup``
  variant labels name the chunk programs.

``make chunk-check`` runs exactly this file.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from seldon_core_tpu.disagg.handoff import (
    build_handoff_frame,
    decode_handoff,
)
from seldon_core_tpu.executor.generation import (
    GenerationScheduler,
    GenerativeComponent,
    GenerativeModel,
)
from seldon_core_tpu.models import llama

run = asyncio.run


@pytest.fixture(scope="module")
def tiny():
    import jax

    cfg = llama.Config.tiny(max_seq=128)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


# mixed lengths: several longer than one 16-token chunk, one shorter
PROMPTS = [
    list(range(5, 50)),
    [30, 7],
    list(range(1, 70)),
    [11, 13, 17, 19, 23],
]


def _generate(
    cfg, params, prompts, *, max_new=9, temperature=0.0, seed=None, **kw
):
    model = GenerativeModel(cfg, params, n_slots=4, decode_block=4, **kw)
    sched = GenerationScheduler(model)
    if seed is not None:
        sched._seed = seed

    async def go():
        try:
            return await asyncio.gather(
                *(
                    sched.submit(
                        np.asarray(p, np.int32),
                        max_new_tokens=max_new,
                        temperature=temperature,
                    )
                    for p in prompts
                )
            )
        finally:
            await sched.close()

    return run(go()), model


class TestChunkedPinnedEqual:
    """Chunked prefill must be a pure scheduling optimization: the written
    K/V and every emitted token are bit-identical to the monolithic path."""

    def test_greedy_chunked_equals_monolithic(self, tiny):
        cfg, params = tiny
        base, _ = _generate(cfg, params, PROMPTS)
        chunk, model = _generate(cfg, params, PROMPTS, prefill_chunk=16)
        for p, a, b in zip(PROMPTS, base, chunk):
            assert np.array_equal(a, b), (len(p), a.tolist(), b.tolist())
        assert model.prefill_chunks >= 2  # the long prompts really chunked
        assert model.prefills == len(PROMPTS)  # one LOGICAL prefill each

    def test_seeded_topk_chunked_equals_monolithic(self, tiny):
        cfg, params = tiny
        kw = dict(temperature=0.9, seed=4242)
        base, _ = _generate(cfg, params, PROMPTS, top_k=4, **kw)
        chunk, model = _generate(
            cfg, params, PROMPTS, top_k=4, prefill_chunk=16, **kw
        )
        for a, b in zip(base, chunk):
            assert np.array_equal(a, b), (a.tolist(), b.tolist())
        assert model.prefill_chunks >= 2

    def test_chunked_with_prefix_reuse(self, tiny):
        """Reuse composes: the matched prefix skips its chunks entirely,
        only the novel suffix is chunked."""
        cfg, params = tiny
        prefix = list(range(7, 39))  # 2 full 16-token blocks
        prompts = [prefix + list(range(40 + i, 60 + i)) for i in range(3)]

        def gen(**kw):
            model = GenerativeModel(
                cfg, params, n_slots=2, decode_block=4, kv_block_size=16, **kw
            )
            sched = GenerationScheduler(model)

            async def go():
                try:
                    # sequential: later prompts reuse absorbed prefix blocks
                    return [
                        await sched.submit(
                            np.asarray(p, np.int32), max_new_tokens=6
                        )
                        for p in prompts
                    ]
                finally:
                    await sched.close()

            return run(go()), model

        base, _ = gen()
        chunk, model = gen(prefill_chunk=16, prefix_reuse=True)
        for a, b in zip(base, chunk):
            assert np.array_equal(a, b), (a.tolist(), b.tolist())
        assert model.prefills_reused >= 1
        assert model.prefill_chunks >= 2

    def test_chunked_int8_kv(self, tiny):
        cfg, params = tiny
        base, _ = _generate(cfg, params, PROMPTS, kv_cache_dtype="int8")
        chunk, _ = _generate(
            cfg, params, PROMPTS, kv_cache_dtype="int8", prefill_chunk=16
        )
        for a, b in zip(base, chunk):
            assert np.array_equal(a, b), (a.tolist(), b.tolist())

    def test_chunked_spec_draft_greedy_pinned(self, tiny):
        """Chunking + fused speculation together still match the plain
        sequential path bit-for-bit on greedy."""
        cfg, params = tiny
        base, _ = _generate(cfg, params, PROMPTS)
        both, model = _generate(
            cfg, params, PROMPTS, spec_draft=3, prefill_chunk=16
        )
        for a, b in zip(base, both):
            assert np.array_equal(a, b), (a.tolist(), b.tolist())
        assert model.prefill_chunks >= 2

    def test_chunked_tp2_sharded_mesh(self, tiny):
        from seldon_core_tpu.parallel import best_mesh

        cfg, params = tiny
        mesh = best_mesh(2, tp=2)

        def gen(**kw):
            model = GenerativeModel(
                cfg, params, n_slots=2, decode_block=4, mesh=mesh,
                param_axes=llama.param_logical_axes(params), **kw
            )
            sched = GenerationScheduler(model)

            async def go():
                try:
                    return [
                        await sched.submit(
                            np.asarray(p, np.int32), max_new_tokens=6
                        )
                        for p in PROMPTS[:2]
                    ]
                finally:
                    await sched.close()

            return run(go()), model

        base, _ = gen()
        chunk, model = gen(prefill_chunk=16)
        for a, b in zip(base, chunk):
            assert np.array_equal(a, b), (a.tolist(), b.tolist())
        assert model.prefill_chunks >= 2

    def test_chunked_disagg_handoff(self, tiny):
        """A chunk-prefilled slot exports byte-identical KV: the handoff
        decode matches the unified (unchunked) run exactly."""
        cfg, params = tiny
        prompt = np.asarray(list(range(7, 42)), np.int32)
        base, _ = _generate(cfg, params, [prompt])

        model_a = GenerativeModel(
            cfg, params, n_slots=2, decode_block=4, prefill_chunk=16
        )
        model_b = GenerativeModel(cfg, params, n_slots=2, decode_block=4)
        sched_a = GenerationScheduler(model_a)
        sched_b = GenerationScheduler(model_b)

        async def go():
            try:
                slot, tok1 = await sched_a.submit_prefill(prompt)
                frame = build_handoff_frame(
                    model_a, slot, prompt, tok1, max_new_tokens=9
                )
                sched_a.release_external(slot)
                payload = decode_handoff(frame)
                return await sched_b.submit_imported(
                    payload["prompt"],
                    first_token=payload["first_token"],
                    k=payload["k"],
                    v=payload["v"],
                    max_new_tokens=9,
                )
            finally:
                await sched_a.close()
                await sched_b.close()

        got = run(go())
        np.testing.assert_array_equal(got, base[0])
        assert model_a.prefill_chunks >= 2  # the export WAS chunk-built

    def test_eos_stops_exactly_with_chunking(self, tiny):
        cfg, params = tiny
        prompt = np.asarray(list(range(3, 40)), np.int32)
        base, _ = _generate(cfg, params, [prompt], max_new=12)
        eos = int(base[0][4])
        stop_at = int(np.argmax(base[0] == eos)) + 1

        def gen(**kw):
            model = GenerativeModel(
                cfg, params, n_slots=2, decode_block=4, **kw
            )
            sched = GenerationScheduler(model)

            async def go():
                try:
                    return await sched.submit(
                        prompt, max_new_tokens=12, eos_id=eos
                    )
                finally:
                    await sched.close()

            return run(go())

        a = gen()
        b = gen(prefill_chunk=16)
        assert np.array_equal(a, b), (a.tolist(), b.tolist())
        assert a.size == stop_at


async def _interleaved_flood(cfg, params, *, chunked: bool):
    """One interactive stream decoding while long-prompt admissions flood
    in: the scenario chunking exists for."""
    model = GenerativeModel(
        cfg, params, n_slots=3, decode_block=4,
        prefill_chunk=16 if chunked else 0,
        name=f"chunk-flood-{int(chunked)}",
    )
    sched = GenerationScheduler(model)
    long_p = np.arange(1, 80, dtype=np.int32)
    interactive = asyncio.create_task(
        sched.submit(np.asarray([5, 9, 2], np.int32), max_new_tokens=40)
    )
    await asyncio.sleep(0.3)  # let the stream reach steady-state decode
    floods = [
        asyncio.create_task(sched.submit(long_p, max_new_tokens=2))
        for _ in range(3)
    ]
    out = await interactive
    await asyncio.gather(*floods)
    await sched.close()
    return out, model


class TestChunkedInterleave:
    def test_flood_admissions_are_chunk_paced_and_greedy_pinned(self, tiny):
        cfg, params = tiny
        base, _ = run(_interleaved_flood(cfg, params, chunked=False))
        chunk, model = run(_interleaved_flood(cfg, params, chunked=True))
        assert np.array_equal(base, chunk), (base.tolist(), chunk.tolist())
        # the floods really went through the paced pipeline (80-token
        # prompt over 16-token chunks = 5 chunks each)
        assert model.prefill_chunks >= 10

    def test_host_sync_audit_with_chunking_on(self, tiny):
        """Chunking must not reintroduce per-token host syncs: still <= 1
        sync per fused block — intermediate chunks dispatch unfetched, only
        each admission's final chunk materializes its first token."""
        from seldon_core_tpu.obs import host_sync_snapshot

        cfg, params = tiny
        name = "chunk-sync-audit"
        before = host_sync_snapshot().get(name, 0)

        async def go():
            model = GenerativeModel(
                cfg, params, n_slots=3, decode_block=8, prefill_chunk=16,
                name=name,
            )
            sched = GenerationScheduler(model, overlap=True)
            interactive = asyncio.create_task(
                sched.submit(np.asarray([5, 9, 2], np.int32),
                             max_new_tokens=24)
            )
            await asyncio.sleep(0.3)
            floods = [
                asyncio.create_task(
                    sched.submit(np.arange(1, 60, dtype=np.int32),
                                 max_new_tokens=2)
                )
                for _ in range(2)
            ]
            out = await interactive
            await asyncio.gather(*floods)
            await sched.close()
            return out, model

        out, model = run(go())
        assert out.size == 24
        syncs = host_sync_snapshot().get(name, 0) - before
        blocks = model.steps / model.decode_block
        assert syncs <= blocks + 4, (
            f"{syncs} host syncs for {blocks} fused blocks"
        )

    def test_itl_ledger_records_delivery_gaps(self, tiny):
        cfg, params = tiny
        _, model = _generate(cfg, params, PROMPTS, max_new=12)
        snap = model.spec_snapshot()
        assert snap["itl_samples"] > 0
        assert snap["itl_p50_ms"] is not None
        assert snap["itl_p99_ms"] >= snap["itl_p50_ms"]

    def test_itl_histogram_metric_exists(self):
        from seldon_core_tpu.utils.metrics import DEFAULT

        DEFAULT.itl.labels("itl-smoke").observe(0.01)
        assert b"seldon_itl_seconds" in DEFAULT.expose()


class TestChunkConfig:
    def test_chunk_rounds_up_to_block_multiple(self, tiny):
        cfg, params = tiny
        model = GenerativeModel(
            cfg, params, n_slots=2, prefill_chunk=20, kv_block_size=16
        )
        assert model.prefill_chunk == 32

    def test_env_opt_in(self, tiny, monkeypatch):
        cfg, params = tiny
        monkeypatch.setenv("SCT_PREFILL_CHUNK", "16")
        model = GenerativeModel(cfg, params, n_slots=2)
        assert model.prefill_chunk == 16
        monkeypatch.setenv("SCT_DECODE_KERNEL", "1")
        model = GenerativeModel(cfg, params, n_slots=2)
        assert model.decode_kernel is True

    def test_kernel_disabled_on_mesh(self, tiny):
        """The Pallas kernel does not partition over a mesh yet: a sharded
        deployment falls back to the XLA gather path with a warning."""
        from seldon_core_tpu.parallel import best_mesh

        cfg, params = tiny
        model = GenerativeModel(
            cfg, params, n_slots=2, mesh=best_mesh(2, tp=2),
            param_axes=llama.param_logical_axes(params), decode_kernel=True,
        )
        assert model.decode_kernel is False


class TestKernelGeneration:
    """Generation-level pin: the fused Pallas decode step emits the same
    greedy stream as the XLA gather path (interpret mode on CPU)."""

    def test_kernel_generation_pinned_equal(self, tiny):
        cfg, params = tiny
        base, _ = _generate(cfg, params, PROMPTS)
        kern, model = _generate(cfg, params, PROMPTS, decode_kernel=True)
        for a, b in zip(base, kern):
            assert np.array_equal(a, b), (a.tolist(), b.tolist())
        assert model.decode_kernel is True

    def test_kernel_int8_generation_pinned_equal(self, tiny):
        cfg, params = tiny
        base, _ = _generate(cfg, params, PROMPTS[:2], kv_cache_dtype="int8")
        kern, _ = _generate(
            cfg, params, PROMPTS[:2], kv_cache_dtype="int8",
            decode_kernel=True,
        )
        for a, b in zip(base, kern):
            assert np.array_equal(a, b), (a.tolist(), b.tolist())


class TestProgramKeyAudit:
    """ISSUE 8 satellite: ``prefill_chunk`` and ``decode_kernel`` must ride
    the compiled-program cache keys — two deployments differing only in
    chunking/kernel config can never share a compiled step."""

    def _touch(self, model):
        model.step_k(
            np.zeros(model.n_slots, np.int32),
            np.zeros(model.n_slots, bool),
            np.zeros(model.n_slots, np.float32),
            0,
            np.full(model.n_slots, -1, np.int32),
            np.zeros(model.n_slots, np.int32),
            model.decode_block,
            window=64,
        )

    def test_decode_k_keys_fold_chunk_and_kernel(self, tiny):
        cfg, params = tiny
        variants = [{}, {"prefill_chunk": 32}, {"decode_kernel": True}]
        keys = []
        for kw in variants:
            model = GenerativeModel(
                cfg, params, n_slots=2, decode_block=2, **kw
            )
            self._touch(model)
            (key,) = model._decode_k_jit.keys()
            keys.append(key)
        assert all(k[:2] == (2, 64) for k in keys)
        assert len(set(keys)) == len(keys), keys

    def test_prefill_suffix_keys_fold_chunk(self, tiny):
        """A chunked admission's suffix programs key on the full static
        config (regression: bare (bucket, window) keys would let a
        chunked and an unchunked deployment share a program)."""
        cfg, params = tiny
        model = GenerativeModel(
            cfg, params, n_slots=2, decode_block=2, prefill_chunk=16
        )
        model.admit(0, np.arange(1, 40, dtype=np.int32), 0.0, 0)
        assert model._prefill_suffix_jit, "long admission must chunk"
        for key in model._prefill_suffix_jit:
            assert key[2:] == model._program_config, key

    def test_program_config_covers_chunk_and_kernel(self, tiny):
        cfg, params = tiny
        model = GenerativeModel(
            cfg, params, n_slots=2, decode_block=2, top_k=3,
            prefill_chunk=32, decode_kernel=True,
        )
        assert model._program_config == (
            3, 0, model.spec_ngram, model.spec_hist, None, 0, None, None,
            32, True, 0, 0, False,
        )


class TestWarmupChunkVariants:
    def test_warmup_names_chunk_programs(self, tiny):
        """/stats/warmup attribution: with chunking on the variant list
        names the chunk suffix programs per prefix window (e.g.
        ``prefill:b32:w64[chunk32]``) so readiness provably covered the
        chunk pipeline, and monolithic labels stop at the chunk size."""
        cfg, params = tiny
        comp = GenerativeComponent(
            GenerativeModel(
                cfg, params, n_slots=2, decode_block=4, prefill_chunk=32,
            )
        )
        n = comp.warmup()
        variants = comp.warmup_variants()
        assert len(variants) == n
        assert any(
            v.startswith("prefill:b32:w") and "[chunk32]" in v
            for v in variants
        ), variants
        # no monolithic label beyond the chunk size: those programs are
        # never compiled (long admissions run the chunk pipeline)
        assert not any(
            v.startswith("prefill:b64") or v.startswith("prefill:b128")
            for v in variants
        ), variants

        async def _close():
            await comp.close()

        run(_close())
