# Iris scorer in R — served by the wrappers/r runtime (plumber).
# EXACTLY the coefficients of examples/iris/IrisClassifier.py (pinned equal
# by tests/test_examples.py), so the python and R runtimes answer the same.

# rows: setosa, versicolor, virginica; cols: sepal_l, sepal_w, petal_l,
# petal_w, bias
W <- matrix(c(
   0.4,  1.4, -2.2, -1.0,  0.3,
   0.4, -1.6,  0.4, -1.3,  1.2,
  -1.7, -1.5,  2.4,  2.4, -1.0
), nrow = 3, byrow = TRUE)

names_out <- c("setosa", "versicolor", "virginica")

predict_model <- function(X) {
  scores <- X %*% t(W[, 1:4]) + matrix(W[, 5], nrow(X), 3, byrow = TRUE)
  e <- exp(scores - apply(scores, 1, max))
  e / rowSums(e)
}
