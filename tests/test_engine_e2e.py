"""End-to-end engine tests over real HTTP.

The in-process analogue of the reference's MockMvc suite
(reference: engine/src/test/java/io/seldon/engine/api/rest/
TestRestClientController.java:1-103 — REST against the default SIMPLE_MODEL
graph) plus a cross-service test where a graph node lives behind a real
microservice HTTP server (the reference can only do this on a live cluster).
"""

import asyncio
import os

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from seldon_core_tpu.engine.app import EngineApp
from seldon_core_tpu.engine.service import PredictionService, load_predictor_spec
from seldon_core_tpu.graph.spec import PredictorSpec
from seldon_core_tpu.runtime.server import MicroserviceApp
from seldon_core_tpu.graph.units import EpsilonGreedy

run = asyncio.run


async def _engine_client(predictor: PredictorSpec, components=None) -> TestClient:
    service = PredictionService(predictor, components=components)
    app = EngineApp(service).build()
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


def default_predictor() -> PredictorSpec:
    return load_predictor_spec(environ={})


REQ = {"data": {"ndarray": [[1.0, 2.0, 3.0]]}}


class TestEngineRest:
    def test_predictions_default_graph(self):
        async def go():
            client = await _engine_client(default_predictor())
            try:
                resp = await client.post("/api/v0.1/predictions", json=REQ)
                assert resp.status == 200
                body = await resp.json()
                assert body["status"]["status"] == "SUCCESS"
                assert body["data"]["ndarray"] == [[0.1, 0.9, 0.5]]
                assert body["data"]["names"] == ["class0", "class1", "class2"]
                assert len(body["meta"]["puid"]) >= 32
            finally:
                await client.close()

        run(go())

    def test_form_encoded_compat(self):
        # the reference engine form-POSTs json=<msg> between services
        async def go():
            client = await _engine_client(default_predictor())
            try:
                import json as j

                resp = await client.post(
                    "/api/v1.0/predictions", data={"json": j.dumps(REQ)}
                )
                assert resp.status == 200
                body = await resp.json()
                assert body["data"]["ndarray"] == [[0.1, 0.9, 0.5]]
            finally:
                await client.close()

        run(go())

    def test_bad_json_is_400(self):
        async def go():
            client = await _engine_client(default_predictor())
            try:
                resp = await client.post(
                    "/api/v0.1/predictions",
                    data=b"{not json",
                    headers={"Content-Type": "application/json"},
                )
                assert resp.status == 400
                body = await resp.json()
                assert body["status"]["status"] == "FAILURE"
            finally:
                await client.close()

        run(go())

    def test_ping_ready_pause_cycle(self):
        async def go():
            client = await _engine_client(default_predictor())
            try:
                assert (await client.get("/ping")).status == 200
                assert (await client.get("/ready")).status == 200
                assert (await client.get("/pause")).status == 200
                assert (await client.get("/ready")).status == 503
                # paused engine still serves traffic (drain semantics,
                # reference: RestClientController.java pause only flips ready)
                assert (await client.post("/api/v0.1/predictions", json=REQ)).status == 200
                assert (await client.get("/unpause")).status == 200
                assert (await client.get("/ready")).status == 200
            finally:
                await client.close()

        run(go())

    def test_prometheus_scrape(self):
        async def go():
            client = await _engine_client(default_predictor())
            try:
                await client.post("/api/v0.1/predictions", json=REQ)
                resp = await client.get("/prometheus")
                text = await resp.text()
                assert "seldon_api_engine_server_requests_duration_seconds" in text
            finally:
                await client.close()

        run(go())

    def test_feedback_updates_bandit(self):
        predictor = PredictorSpec.model_validate(
            {
                "name": "ab",
                "graph": {
                    "name": "eg",
                    "type": "ROUTER",
                    "implementation": "EPSILON_GREEDY",
                    "parameters": [
                        {"name": "epsilon", "value": "0.0", "type": "FLOAT"}
                    ],
                    "children": [
                        {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                        {"name": "b", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                    ],
                },
            }
        )

        async def go():
            service = PredictionService(predictor)
            app = EngineApp(service).build()
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                resp = await client.post("/api/v0.1/predictions", json=REQ)
                body = await resp.json()
                routed = body["meta"]["routing"]["eg"]
                fb = {"request": REQ, "response": body, "reward": 1.0}
                resp = await client.post("/api/v0.1/feedback", json=fb)
                assert resp.status == 200
                router = service.walker.root.client.component
                assert isinstance(router, EpsilonGreedy)
                assert router.pulls[routed] == 1
                assert router.value[routed] == 1.0
            finally:
                await client.close()

        run(go())


class TestWarmupReadiness:
    """Readiness gates on XLA warmup (round-2 item #7): /ready stays 503
    until every JAX unit's bucket ladder is compiled."""

    JAX_PREDICTOR = {
        "name": "warm",
        "graph": {
            "name": "m",
            "type": "MODEL",
            "implementation": "JAX_MODEL",
            "parameters": [
                {"name": "family", "value": "mlp", "type": "STRING"},
                {"name": "preset", "value": "tiny", "type": "STRING"},
            ],
        },
    }

    def test_ready_flips_after_warmup(self):
        async def go():
            service = PredictionService(PredictorSpec.model_validate(self.JAX_PREDICTOR))
            app = EngineApp(service).build()
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                deadline = asyncio.get_event_loop().time() + 120
                status = None
                while asyncio.get_event_loop().time() < deadline:
                    status = (await client.get("/ready")).status
                    if status == 200:
                        break
                    await asyncio.sleep(0.1)
                assert status == 200, "never became ready"
                # every bucket of the JAX unit was compiled before ready
                assert service.warmup_report is not None
                model = service.walker.root.client.component.model
                assert service.warmup_report["m"] == len(model.buckets.sizes)
                resp = await client.post(
                    "/api/v0.1/predictions",
                    json={"data": {"ndarray": [[0.0] * 16]}},
                )
                assert resp.status == 200
            finally:
                await client.close()

        run(go())

    def test_simple_graph_ready_immediately(self):
        async def go():
            client = await _engine_client(default_predictor())
            try:
                # no JAX units -> warmed synchronously at startup
                assert (await client.get("/ready")).status == 200
            finally:
                await client.close()

        run(go())

    def test_warmup_disabled_by_env(self, monkeypatch=None):
        import os
        import unittest.mock as mock

        async def go():
            with mock.patch.dict(os.environ, {"ENGINE_WARMUP": "0"}):
                service = PredictionService(PredictorSpec.model_validate(self.JAX_PREDICTOR))
                app = EngineApp(service).build()
                client = TestClient(TestServer(app))
                await client.start_server()
                try:
                    assert (await client.get("/ready")).status == 200
                    assert service.warmup_report is None
                finally:
                    await client.close()

        run(go())


class TestStrictGrpcBoot:
    def test_grpc_bind_conflict_fails_boot(self):
        from seldon_core_tpu.engine.app import make_grpc_startup
        from seldon_core_tpu.engine.grpc_app import start_engine_grpc

        async def go():
            service = PredictionService(default_predictor())
            first = await start_engine_grpc(service, 0)
            port = first.bound_port
            try:
                service2 = PredictionService(default_predictor())
                app = EngineApp(service2).build()
                app.on_startup.append(make_grpc_startup(service2, port))
                client = TestClient(TestServer(app))
                import pytest as _pytest

                # grpc's own bind error or our bound==0 guard, depending on
                # grpcio version — either way boot must fail loudly
                with _pytest.raises(RuntimeError, match="bind"):
                    await client.start_server()
                await client.close()
            finally:
                await first.stop(grace=0)

        run(go())

    def test_grpc_optional_env_serves_rest_only(self):
        import os
        import unittest.mock as mock

        from seldon_core_tpu.engine.app import make_grpc_startup
        from seldon_core_tpu.engine.grpc_app import start_engine_grpc

        async def go():
            service = PredictionService(default_predictor())
            first = await start_engine_grpc(service, 0)
            port = first.bound_port
            try:
                with mock.patch.dict(os.environ, {"ENGINE_GRPC_OPTIONAL": "1"}):
                    service2 = PredictionService(default_predictor())
                    app = EngineApp(service2).build()
                    app.on_startup.append(make_grpc_startup(service2, port))
                    client = TestClient(TestServer(app))
                    await client.start_server()
                    resp = await client.post("/api/v0.1/predictions", json=REQ)
                    assert resp.status == 200
                    await client.close()
            finally:
                await first.stop(grace=0)

        run(go())


class TestCrossServiceGraph:
    """Engine orchestrating a remote REST microservice — process boundary #2
    of the reference hot path (SURVEY §3.1) exercised in-process."""

    def test_remote_model_node(self):
        class TimesTen:
            def predict(self, X, names):
                return X * 10

            def tags(self):
                return {"remote": True}

        async def go():
            ms_app = MicroserviceApp(TimesTen(), name="m").build()
            ms_server = TestServer(ms_app)
            await ms_server.start_server()
            port = ms_server.port

            predictor = PredictorSpec.model_validate(
                {
                    "name": "p",
                    "graph": {
                        "name": "remote-model",
                        "type": "MODEL",
                        "endpoint": {
                            "service_host": "127.0.0.1",
                            "service_port": port,
                            "type": "REST",
                        },
                    },
                }
            )
            client = await _engine_client(predictor)
            try:
                resp = await client.post("/api/v0.1/predictions", json=REQ)
                assert resp.status == 200
                body = await resp.json()
                assert body["data"]["ndarray"] == [[10.0, 20.0, 30.0]]
                assert body["meta"]["tags"] == {"remote": True}
            finally:
                await client.close()
                await ms_server.close()

        run(go())

    def test_remote_router_and_feedback(self):
        class PickOne:
            def __init__(self):
                self.rewards = []

            def route(self, X, names):
                return 1

            def send_feedback(self, X, names, reward, truth=None, routing=None):
                self.rewards.append((reward, routing))

        router = PickOne()

        async def go():
            ms_server = TestServer(MicroserviceApp(router, name="r").build())
            await ms_server.start_server()

            predictor = PredictorSpec.model_validate(
                {
                    "name": "p",
                    "graph": {
                        "name": "r",
                        "type": "ROUTER",
                        "endpoint": {
                            "service_host": "127.0.0.1",
                            "service_port": ms_server.port,
                            "type": "REST",
                        },
                        "children": [
                            {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                            {"name": "b", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                        ],
                    },
                }
            )
            client = await _engine_client(predictor)
            try:
                resp = await client.post("/api/v0.1/predictions", json=REQ)
                body = await resp.json()
                assert body["meta"]["routing"]["r"] == 1
                fb = {"request": REQ, "response": body, "reward": 0.7}
                assert (await client.post("/api/v0.1/feedback", json=fb)).status == 200
                assert router.rewards == [(0.7, 1)]
            finally:
                await client.close()
                await ms_server.close()

        run(go())

    def test_remote_unit_error_propagates_500(self):
        async def go():
            predictor = PredictorSpec.model_validate(
                {
                    "name": "p",
                    "graph": {
                        "name": "gone",
                        "type": "MODEL",
                        "endpoint": {
                            "service_host": "127.0.0.1",
                            "service_port": 1,  # nothing listens here
                            "type": "REST",
                        },
                    },
                }
            )
            client = await _engine_client(predictor)
            try:
                resp = await client.post("/api/v0.1/predictions", json=REQ)
                assert resp.status == 500
                body = await resp.json()
                assert body["status"]["status"] == "FAILURE"
                assert "unreachable" in body["status"]["reason"]
            finally:
                await client.close()

        run(go())


class TestMeshServing:
    """JAX units shard over a serving mesh from the `mesh`/`sharding` graph
    parameters (VERDICT r2 #6: fsdp in the serving path, not just the
    training dryrun).  8 virtual devices: dp=2 x fsdp=2 x tp=2."""

    MESH_PREDICTOR = {
        "name": "meshy",
        "graph": {
            "name": "m",
            "type": "MODEL",
            "implementation": "JAX_MODEL",
            "parameters": [
                {"name": "family", "value": "mlp", "type": "STRING"},
                {"name": "preset", "value": "tiny", "type": "STRING"},
                {"name": "mesh", "value": "dp=2,fsdp=2,tp=2", "type": "STRING"},
                {"name": "sharding", "value": "fsdp", "type": "STRING"},
            ],
        },
    }

    def test_fsdp_tp_mesh_serving_matches_unsharded(self):
        import jax

        from seldon_core_tpu.models.registry import build_compiled

        async def go():
            service = PredictionService(PredictorSpec.model_validate(self.MESH_PREDICTOR))
            app = EngineApp(service).build()
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                deadline = asyncio.get_event_loop().time() + 120
                while asyncio.get_event_loop().time() < deadline:
                    if (await client.get("/ready")).status == 200:
                        break
                    await asyncio.sleep(0.1)
                model = service.walker.root.client.component.model
                # params genuinely sharded: dense kernels are (embed->fsdp,
                # mlp->tp) under FSDP_RULES
                specs = {
                    str(leaf.sharding.spec)
                    for leaf in jax.tree.leaves(model.params)
                }
                assert any("fsdp" in s for s in specs), specs
                assert any("tp" in s for s in specs), specs
                rows = np.random.default_rng(3).normal(size=(3, 16)).tolist()
                resp = await client.post(
                    "/api/v0.1/predictions", json={"data": {"ndarray": rows}}
                )
                assert resp.status == 200
                got = np.asarray((await resp.json())["data"]["ndarray"])
                return got, rows
            finally:
                await client.close()

        got, rows = run(go())
        # same rng seed -> same init params: the sharded serving output must
        # match a plain single-device model bit-for-bit-ish
        ref = build_compiled("mlp", preset="tiny")(np.asarray(rows, np.float32))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_bad_mesh_parameter_rejected(self):
        from seldon_core_tpu.graph.units import GraphUnitError, create_builtin
        from seldon_core_tpu.graph.spec import Implementation

        import pytest as _pytest

        with _pytest.raises(GraphUnitError, match="mesh"):
            create_builtin(
                Implementation.JAX_MODEL,
                {"family": "mlp", "preset": "tiny", "mesh": "tp=banana"},
            )
        with _pytest.raises(GraphUnitError, match="sharding"):
            create_builtin(
                Implementation.JAX_MODEL,
                {"family": "mlp", "preset": "tiny", "sharding": "nope"},
            )


class TestHopRetries:
    """One blipped connection must not become a user-visible 500
    (round-3 item: the reference had HttpRetryHandler; round 2 had none)."""

    def test_rest_hop_retries_transient_503(self):
        from seldon_core_tpu.engine.transport import RestNodeClient
        from seldon_core_tpu.graph.spec import Endpoint, PredictiveUnitSpec, UnitType
        import aiohttp
        from aiohttp import web as _web

        calls = {"n": 0}

        async def flaky(request):
            calls["n"] += 1
            if calls["n"] < 3:
                return _web.json_response({"status": {"info": "warming"}}, status=503)
            return _web.json_response({"data": {"ndarray": [[9.0]]}})

        async def go():
            app = _web.Application()
            app.router.add_post("/predict", flaky)
            srv = TestServer(app)
            await srv.start_server()
            session = aiohttp.ClientSession()
            try:
                spec = PredictiveUnitSpec(
                    name="m",
                    type=UnitType.MODEL,
                    endpoint=Endpoint(
                        service_host="127.0.0.1", service_port=srv.port, type="REST"
                    ),
                )
                client = RestNodeClient(spec, session)
                from seldon_core_tpu.contract import Payload

                out = await client.transform_input(Payload.from_array(np.array([[1.0]])))
                return out.array, calls["n"]
            finally:
                await session.close()
                await srv.close()

        arr, n = run(go())
        assert n == 3  # two retries then success
        assert arr.tolist() == [[9.0]]

    def test_feedback_not_retried_after_send(self):
        """A 503 AFTER the request reached the unit must not be retried for
        feedback — a bandit reward must never double-count."""
        from seldon_core_tpu.engine.transport import RemoteUnitError, RestNodeClient
        from seldon_core_tpu.graph.spec import Endpoint, PredictiveUnitSpec, UnitType
        import aiohttp
        import pytest as _pytest
        from aiohttp import web as _web

        calls = {"n": 0}

        async def always_503(request):
            calls["n"] += 1
            return _web.json_response({"status": {"info": "no"}}, status=503)

        async def go():
            app = _web.Application()
            app.router.add_post("/send-feedback", always_503)
            srv = TestServer(app)
            await srv.start_server()
            session = aiohttp.ClientSession()
            try:
                spec = PredictiveUnitSpec(
                    name="m",
                    type=UnitType.MODEL,
                    endpoint=Endpoint(
                        service_host="127.0.0.1", service_port=srv.port, type="REST"
                    ),
                )
                client = RestNodeClient(spec, session)
                from seldon_core_tpu.contract import FeedbackPayload, Payload

                fb = FeedbackPayload(
                    request=Payload.from_array(np.array([[1.0]])), reward=1.0
                )
                with _pytest.raises(RemoteUnitError):
                    await client.send_feedback(fb, None)
                return calls["n"]
            finally:
                await session.close()
                await srv.close()

        assert run(go()) == 1  # exactly one attempt


class TestTracing:
    """Opt-in request tracing (meta.tags.sct_trace_ms) and the XLA profiler
    endpoints — SURVEY §5 asked for both; the reference had only JMX and
    log lines."""

    def test_trace_header_adds_per_node_timings(self):
        async def go():
            graph = {
                "name": "eg", "type": "ROUTER", "implementation": "SIMPLE_ROUTER",
                "children": [
                    {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                ],
            }
            client = await _engine_client(
                PredictorSpec.model_validate({"name": "p", "graph": graph})
            )
            try:
                resp = await client.post(
                    "/api/v0.1/predictions", json=REQ,
                    headers={"X-Seldon-Trace": "1"},
                )
                traced = (await resp.json())["meta"]["tags"]["sct_trace_ms"]
                resp2 = await client.post("/api/v0.1/predictions", json=REQ)
                plain = (await resp2.json())["meta"].get("tags", {})
                return traced, plain
            finally:
                await client.close()

        traced, plain = run(go())
        assert set(traced) == {"eg", "a"}
        assert all(isinstance(v, float) for v in traced.values())
        assert traced["eg"] >= traced["a"]  # parent includes child
        assert "sct_trace_ms" not in plain  # zero cost unless asked

    def test_profile_endpoints_round_trip(self, tmp_path):
        async def go():
            client = await _engine_client(default_predictor())
            try:
                r1 = await client.post("/profile/start", json={"dir": str(tmp_path)})
                r_conflict = await client.post("/profile/start", json={})
                r2 = await client.post("/profile/stop")
                r_idle = await client.post("/profile/stop")
                return r1.status, r_conflict.status, r2.status, r_idle.status
            finally:
                await client.close()

        s1, sc, s2, si = run(go())
        assert (s1, sc, s2, si) == (200, 409, 200, 409)
        import os as _os

        assert any(_os.scandir(str(tmp_path)))  # trace artifacts written


class TestTraceContextPropagation:
    """W3C traceparent headers flow engine -> remote unit, so an external
    OTel collector can stitch spans across the graph.  Since the obs layer
    landed, each hop re-parents the span id (the engine/node spans are real
    now) and a trace-naive request gets a MINTED trace instead of none —
    the invariants are trace-id continuity and no cross-request leaks."""

    def test_traceparent_reaches_remote_unit(self):
        import aiohttp
        from aiohttp import web as _web

        seen = []

        async def unit(request):
            seen.append(request.headers.get("traceparent"))
            return _web.json_response({"data": {"ndarray": [[1.0]]}})

        async def go():
            app = _web.Application()
            app.router.add_post("/predict", unit)
            srv = TestServer(app)
            await srv.start_server()
            predictor = PredictorSpec.model_validate(
                {
                    "name": "p",
                    "graph": {
                        "name": "m", "type": "MODEL",
                        "endpoint": {"service_host": "127.0.0.1",
                                     "service_port": srv.port, "type": "REST"},
                    },
                }
            )
            client = await _engine_client(predictor)
            try:
                tp = "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"
                resp = await client.post(
                    "/api/v0.1/predictions", json=REQ,
                    headers={"traceparent": tp},
                )
                assert resp.status == 200
                # a request WITHOUT traceparent must not leak the old one
                resp2 = await client.post("/api/v0.1/predictions", json=REQ)
                assert resp2.status == 200
                return seen, tp
            finally:
                await client.close()
                await srv.close()

        seen, tp = run(go())
        from seldon_core_tpu.utils.tracectx import parse_traceparent

        assert len(seen) == 2 and all(s is not None for s in seen)
        first, second = parse_traceparent(seen[0]), parse_traceparent(seen[1])
        # hop 1 stays in the client's trace (span id re-parented by the
        # engine/node spans, trace id intact)
        assert first is not None and first[0] == parse_traceparent(tp)[0]
        assert seen[0] != tp  # a real span sits between client and unit
        # hop 2 was trace-naive: a fresh MINTED trace, NOT the leaked old one
        assert second is not None and second[0] != first[0]


class TestMultiWorkerIngress:
    """--workers N: SO_REUSEPORT processes sharing one port (the Python
    equivalent of the reference's 16-core multithreaded engine JVM,
    docs/benchmarking.md:19-36).  Each worker owns its own service +
    sub-batchers; kernel accept balancing spreads connections."""

    @pytest.mark.slow
    def test_two_workers_share_one_port(self):
        import json as _json
        import subprocess
        import sys
        import time
        import urllib.request

        env = dict(os.environ)
        env.pop("ENGINE_PREDICTOR", None)  # default stub graph
        env["ENGINE_WARMUP"] = "0"
        proc = subprocess.Popen(
            [sys.executable, "-m", "seldon_core_tpu.engine.app",
             "--port", "18908", "--grpc-port", "18909", "--workers", "2"],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
        )
        try:
            deadline = time.time() + 120
            while True:
                assert proc.poll() is None, "engine died"
                try:
                    with urllib.request.urlopen(
                        "http://127.0.0.1:18908/ready", timeout=2
                    ) as r:
                        if r.status == 200:
                            break
                except OSError:
                    pass
                assert time.time() < deadline, "engine never ready"
                time.sleep(1)

            body = _json.dumps({"data": {"ndarray": [[1.0, 2.0, 3.0]]}}).encode()
            workers = set()
            for _ in range(80):
                req = urllib.request.Request(
                    "http://127.0.0.1:18908/api/v0.1/predictions",
                    data=body,
                    headers={"Content-Type": "application/json",
                             "Connection": "close"},
                )
                with urllib.request.urlopen(req, timeout=10) as r:
                    assert r.status == 200
                    out = _json.loads(r.read())
                    assert out["status"]["status"] == "SUCCESS"
                    workers.add(r.headers.get("X-Engine-Worker"))
                if len(workers) >= 2:
                    break
            assert len(workers) >= 2, (
                f"kernel accept balancing never reached worker 2: {workers}"
            )
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
