"""Graph walker and built-in unit tests.

Mirrors the reference's engine unit suite (reference:
engine/src/test/java/io/seldon/engine/predictors/AverageCombinerTest.java,
RandomABTestUnitTest.java, SimpleModelUnitTest.java) plus walker semantics:
routing map recording, tag merge, feedback replay down the routed path.
"""

import asyncio

import numpy as np
import pytest

from seldon_core_tpu.contract import DataKind, FeedbackPayload, Payload
from seldon_core_tpu.graph import (
    AverageCombiner,
    EpsilonGreedy,
    GraphUnitError,
    GraphWalker,
    MahalanobisOutlier,
    PredictiveUnitSpec,
    RandomABTest,
    SimpleModel,
    ThompsonSampling,
)

run = asyncio.run


def payload(arr, names=None):
    return Payload.from_array(np.asarray(arr, dtype=np.float64), names=names)


def spec(d):
    return PredictiveUnitSpec.from_dict(d)


class TestBuiltinUnits:
    def test_simple_model_constant_row_per_input(self):
        out = SimpleModel().predict(np.zeros((3, 4)), [])
        assert out.shape == (3, 3)
        np.testing.assert_allclose(out[0], [0.1, 0.9, 0.5])

    def test_average_combiner_mean(self):
        comb = AverageCombiner()
        out = comb.aggregate(
            [np.array([[1.0, 2.0]]), np.array([[3.0, 4.0]])], [[], []]
        )
        np.testing.assert_allclose(out, [[2.0, 3.0]])

    def test_average_combiner_shape_mismatch(self):
        with pytest.raises(GraphUnitError):
            AverageCombiner().aggregate(
                [np.ones((1, 2)), np.ones((2, 2))], [[], []]
            )
        with pytest.raises(GraphUnitError):
            AverageCombiner().aggregate([], [])

    def test_random_abtest_distribution(self):
        # seeded → reproducible split close to ratioA (reference:
        # RandomABTestUnitTest uses a fixed seed the same way)
        router = RandomABTest(ratioA=0.7, seed=1337)
        picks = [router.route(np.zeros((1, 1)), []) for _ in range(1000)]
        frac_a = picks.count(0) / len(picks)
        assert 0.65 < frac_a < 0.75
        assert set(picks) <= {0, 1}

    def test_epsilon_greedy_learns_best_branch(self):
        router = EpsilonGreedy(n_branches=3, epsilon=0.1, seed=7)
        # branch 2 always rewards; others never
        for _ in range(200):
            b = router.route(np.zeros((1, 1)), [])
            router.send_feedback(None, [], reward=1.0 if b == 2 else 0.0, routing=b)
        exploit = [router.route(np.zeros((1, 1)), []) for _ in range(100)]
        assert exploit.count(2) > 80

    def test_thompson_sampling_learns(self):
        router = ThompsonSampling(n_branches=2, seed=3)
        for _ in range(300):
            b = router.route(np.zeros((1, 1)), [])
            router.send_feedback(None, [], reward=1.0 if b == 1 else 0.0, routing=b)
        picks = [router.route(np.zeros((1, 1)), []) for _ in range(100)]
        assert picks.count(1) > 80

    def test_mahalanobis_flags_outlier(self):
        det = MahalanobisOutlier()
        rng = np.random.default_rng(0)
        det.score(rng.normal(size=(200, 3)))
        scores = det.score(np.array([[50.0, 50.0, 50.0], [0.0, 0.0, 0.0]]))
        assert scores[0] > 100 * max(scores[1], 1e-9)
        assert "outlier_score" in det.tags()


SIMPLE_GRAPH = {
    "name": "clf",
    "type": "MODEL",
    "implementation": "SIMPLE_MODEL",
}

ABTEST_GRAPH = {
    "name": "ab",
    "type": "ROUTER",
    "implementation": "RANDOM_ABTEST",
    "parameters": [{"name": "ratioA", "value": "1.0", "type": "FLOAT"}],
    "children": [
        {"name": "model-a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
        {"name": "model-b", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
    ],
}

COMBINER_GRAPH = {
    "name": "ens",
    "type": "COMBINER",
    "implementation": "AVERAGE_COMBINER",
    "children": [
        {"name": "m0", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
        {"name": "m1", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
    ],
}


class TestGraphWalker:
    def test_single_model(self):
        w = GraphWalker(spec(SIMPLE_GRAPH))
        out = run(w.predict(payload(np.zeros((2, 4)))))
        assert out.array.shape == (2, 3)
        assert out.names == ["class0", "class1", "class2"]
        assert out.meta.request_path == {"clf": "SimpleModel"}

    def test_router_records_routing(self):
        w = GraphWalker(spec(ABTEST_GRAPH))
        out = run(w.predict(payload(np.zeros((1, 2)))))
        assert out.meta.routing == {"ab": 0}
        np.testing.assert_allclose(out.array, [[0.1, 0.9, 0.5]])

    def test_router_bad_branch_raises(self):
        class BadRouter:
            def route(self, X, names):
                return 7

        g = spec(ABTEST_GRAPH)
        w = GraphWalker(g, components={"ab": BadRouter()})
        with pytest.raises(GraphUnitError):
            run(w.predict(payload(np.zeros((1, 2)))))

    def test_combiner_fans_out_and_averages(self):
        w = GraphWalker(spec(COMBINER_GRAPH))
        out = run(w.predict(payload(np.zeros((2, 2)))))
        np.testing.assert_allclose(out.array, np.tile([0.1, 0.9, 0.5], (2, 1)))
        assert set(out.meta.request_path) == {"ens", "m0", "m1"}

    def test_multiple_children_without_combiner_raises(self):
        g = dict(COMBINER_GRAPH)
        g = {**g, "type": "MODEL", "implementation": "SIMPLE_MODEL", "name": "root"}
        w = GraphWalker(spec(g))
        with pytest.raises(GraphUnitError):
            run(w.predict(payload(np.zeros((1, 2)))))

    def test_transformer_chain_and_tag_merge(self):
        class Doubler:
            def transform_input(self, X, names):
                return X * 2

            def tags(self):
                return {"doubled": True}

        class Halver:
            def transform_output(self, X, names):
                return X / 2

        g = spec(
            {
                "name": "t-in",
                "type": "TRANSFORMER",
                "children": [
                    {
                        "name": "t-out",
                        "type": "OUTPUT_TRANSFORMER",
                        "children": [
                            {
                                "name": "m",
                                "type": "MODEL",
                                "implementation": "SIMPLE_MODEL",
                            }
                        ],
                    }
                ],
            }
        )
        w = GraphWalker(g, components={"t-in": Doubler(), "t-out": Halver()})
        out = run(w.predict(payload(np.ones((1, 2)))))
        np.testing.assert_allclose(out.array, [[0.05, 0.45, 0.25]])
        assert out.meta.tags == {"doubled": True}

    def test_outlier_transformer_tags_scores(self):
        g = spec(
            {
                "name": "outlier",
                "type": "TRANSFORMER",
                "implementation": "MAHALANOBIS_OUTLIER",
                "children": [
                    {"name": "m", "type": "MODEL", "implementation": "SIMPLE_MODEL"}
                ],
            }
        )
        w = GraphWalker(g)
        for _ in range(5):
            out = run(w.predict(payload(np.random.default_rng(1).normal(size=(4, 3)))))
        assert "outlier_score" in out.meta.tags

    def test_async_component(self):
        class AsyncModel:
            async def predict(self, X, names):
                await asyncio.sleep(0)
                return X + 1

        g = spec({"name": "am", "type": "MODEL"})
        w = GraphWalker(g, components={"am": AsyncModel()})
        out = run(w.predict(payload(np.zeros((1, 2)))))
        np.testing.assert_allclose(out.array, [[1.0, 1.0]])

    def test_raw_component_controls_payload(self):
        class RawModel:
            def predict_raw(self, p):
                return Payload.from_array(
                    np.array([[42.0]]), kind=DataKind.TENSOR
                )

        g = spec({"name": "raw", "type": "MODEL"})
        w = GraphWalker(g, components={"raw": RawModel()})
        out = run(w.predict(payload(np.zeros((1, 2)))))
        assert out.kind == DataKind.TENSOR
        np.testing.assert_allclose(out.array, [[42.0]])


class TestFeedbackWalk:
    def _bandit_walker(self):
        g = spec(
            {
                "name": "eg",
                "type": "ROUTER",
                "implementation": "EPSILON_GREEDY",
                "parameters": [
                    {"name": "n_branches", "value": "2", "type": "INT"},
                    {"name": "epsilon", "value": "0.0", "type": "FLOAT"},
                ],
                "children": [
                    {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                    {"name": "b", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                ],
            }
        )
        return GraphWalker(g)

    def test_feedback_reaches_router_on_routed_path(self):
        w = self._bandit_walker()
        req = payload(np.zeros((1, 2)))
        resp = run(w.predict(req))
        assert "eg" in resp.meta.routing
        fb = FeedbackPayload(request=req, response=resp, reward=1.0)
        run(w.send_feedback(fb))
        router = w.root.client.component
        assert router.pulls.sum() == 1
        routed = resp.meta.routing["eg"]
        assert router.value[routed] == 1.0

    def test_feedback_hook_fires(self):
        seen = []
        g = spec(
            {
                "name": "eg",
                "type": "ROUTER",
                "implementation": "EPSILON_GREEDY",
                "children": [
                    {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                    {"name": "b", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                ],
            }
        )
        w = GraphWalker(g, feedback_hook=lambda name, fb: seen.append((name, fb.reward)))
        resp = run(w.predict(payload(np.zeros((1, 2)))))
        run(w.send_feedback(FeedbackPayload(response=resp, reward=0.5)))
        assert seen == [("eg", 0.5)]

    def test_model_send_feedback_called_when_method_listed(self):
        rewards = []

        class FeedbackModel:
            def predict(self, X, names):
                return X

            def send_feedback(self, X, names, reward, truth=None, routing=None):
                rewards.append(reward)

        g = spec(
            {
                "name": "m",
                "type": "MODEL",
                "methods": ["TRANSFORM_INPUT", "SEND_FEEDBACK"],
            }
        )
        w = GraphWalker(g, components={"m": FeedbackModel()})
        resp = run(w.predict(payload(np.zeros((1, 1)))))
        run(w.send_feedback(FeedbackPayload(response=resp, reward=2.0)))
        assert rewards == [2.0]


class TestTagLockScope:
    """The tag-consistency lock must serialize ONLY components that override
    tags() (stateful: outlier scores); JAX model units inherit the stateless
    base tags() and must keep full pipeline concurrency — locking them
    collapsed wire throughput to one device step at a time."""

    def test_jax_component_not_serialized(self):
        from seldon_core_tpu.graph.spec import PredictiveUnitSpec, UnitType
        from seldon_core_tpu.graph.units import SeldonComponent
        from seldon_core_tpu.graph.walker import LocalClient

        class LikeAJaxUnit(SeldonComponent):
            def predict(self, X, names):
                return X

        client = LocalClient(
            PredictiveUnitSpec(name="m", type=UnitType.MODEL), LikeAJaxUnit()
        )
        assert client._tag_lock is None

    def test_stateful_tags_serialized(self):
        from seldon_core_tpu.graph.spec import PredictiveUnitSpec, UnitType
        from seldon_core_tpu.graph.units import MahalanobisOutlier
        from seldon_core_tpu.graph.walker import LocalClient

        client = LocalClient(
            PredictiveUnitSpec(name="od", type=UnitType.TRANSFORMER),
            MahalanobisOutlier(),
        )
        assert client._tag_lock is not None

    def test_duck_typed_tags_serialized(self):
        from seldon_core_tpu.graph.spec import PredictiveUnitSpec, UnitType
        from seldon_core_tpu.graph.walker import LocalClient

        class Duck:
            def predict(self, X, names):
                return X

            def tags(self):
                return {"k": 1}

        client = LocalClient(
            PredictiveUnitSpec(name="d", type=UnitType.MODEL), Duck()
        )
        assert client._tag_lock is not None

    def test_stateful_metrics_serialized_unless_opted_out(self):
        from seldon_core_tpu.executor.component import JaxModelComponent
        from seldon_core_tpu.graph.walker import make_annotation_lock

        class MetricsOnly:
            def predict(self, X, names):
                return X

            def metrics(self):
                return [{"key": "per_request_value", "value": 1.0}]

        assert make_annotation_lock(MetricsOnly()) is not None
        # JAX components opt out (cumulative gauges): locking them would
        # serialize the batching pipeline
        assert getattr(JaxModelComponent, "SAFE_ANNOTATIONS", False) is True


class TestInlineSyncScope:
    def test_builtin_is_inline(self):
        from seldon_core_tpu.graph.spec import PredictiveUnitSpec, UnitType
        from seldon_core_tpu.graph.units import SimpleModel
        from seldon_core_tpu.graph.walker import LocalClient

        client = LocalClient(
            PredictiveUnitSpec(name="m", type=UnitType.MODEL), SimpleModel()
        )
        assert client._inline

    def test_user_subclass_falls_back_to_thread_pool(self):
        """A subclass inherits INLINE_SYNC but may override methods with
        blocking work — it must NOT run on the event loop."""
        from seldon_core_tpu.graph.spec import PredictiveUnitSpec, UnitType
        from seldon_core_tpu.graph.units import SimpleModel
        from seldon_core_tpu.graph.walker import LocalClient

        class MySlowModel(SimpleModel):
            def predict(self, X, names):
                return X  # imagine blocking I/O here

        client = LocalClient(
            PredictiveUnitSpec(name="m", type=UnitType.MODEL), MySlowModel()
        )
        assert not client._inline
