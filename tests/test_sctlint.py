"""sct-lint: engine, the six rules, the CLI, and the repo meta-invariants.

Each rule gets the four-quadrant treatment on synthetic trees under
tmp_path: a seeded violation (CLI exits non-zero), a clean negative, a
suppressed positive (``# sct: <rule>-ok reason``), and a
baseline-matched positive.  The meta-tests then hold the REAL repo to
the same standard: ``make lint-check`` green, the checked-in baseline
minimal (no stale entries) and empty for the must-be-clean dirs, and
the env-var registry covering every quoted ``SCT_*`` literal.
"""

from __future__ import annotations

import json
import re
import textwrap
from pathlib import Path

import pytest

from seldon_core_tpu.runtime import settings
from seldon_core_tpu.tools.sctlint import core
from seldon_core_tpu.tools.sctlint.__main__ import main as sctlint_main
from seldon_core_tpu.tools.sctlint.rules import BY_ID, RULES

REPO = Path(__file__).resolve().parents[1]


def build(tmp_path: Path, files: dict[str, str]) -> Path:
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return tmp_path


def run(root: Path, *args: str) -> int:
    return sctlint_main(["--root", str(root), *args])


def write_baseline(root: Path, entries: list[tuple[str, str, str]]) -> None:
    (root / core.BASELINE_NAME).write_text(json.dumps({
        "version": 1,
        "findings": [
            {"rule": r, "path": p, "snippet": s} for r, p, s in entries
        ],
    }))


# ---------------------------------------------------------------- host-sync

HOT = """\
    import jax
    import numpy as np

    class GenerationScheduler:
        def _run(self):
            return self._fetch()

        def _fetch(self):
            toks = self._decode_jit()
            host = np.asarray(toks)
            return jax.device_get(host)
    """


def test_host_sync_positive(tmp_path, capsys):
    root = build(tmp_path, {"seldon_core_tpu/executor/generation.py": HOT})
    assert run(root, "--rules", "host-sync", "--no-baseline") == 1
    out = capsys.readouterr().out
    assert "[host-sync]" in out
    assert "jax.device_get" in out
    assert "np.asarray" in out  # tainted local coerced to host


def test_host_sync_negative_cold_function(tmp_path):
    # the same syncs OUTSIDE the hot call graph are not the rule's business
    root = build(tmp_path, {"seldon_core_tpu/executor/generation.py": """\
        import jax

        class GenerationScheduler:
            def _run(self):
                return 0

            def debug_dump(self):
                return jax.device_get(self._cache)
        """})
    assert run(root, "--rules", "host-sync", "--no-baseline") == 0


def test_host_sync_suppressed(tmp_path):
    root = build(tmp_path, {"seldon_core_tpu/executor/generation.py": """\
        import jax

        class GenerationScheduler:
            def _run(self):
                # sct: host-sync-ok the one budgeted fetch
                return jax.device_get(self._cache)
        """})
    assert run(root, "--rules", "host-sync", "--no-baseline") == 0


def test_host_sync_baseline_matched(tmp_path):
    root = build(tmp_path, {"seldon_core_tpu/gateway/hot.py": ""})
    build(root, {"seldon_core_tpu/executor/generation.py": HOT})
    # note: executor/ baseline entries are forbidden in the real repo;
    # the ENGINE still honours them (bad_baseline fails the run), so use
    # a custom baseline path to test matching alone
    write_baseline(root, [
        ("host-sync", "seldon_core_tpu/executor/generation.py",
         "host = np.asarray(toks)"),
        ("host-sync", "seldon_core_tpu/executor/generation.py",
         "return jax.device_get(host)"),
    ])
    # matched entries stop being "new" but executor/ entries are
    # themselves findings (baseline-forbidden): the run still fails
    assert run(root, "--rules", "host-sync") == 1


# -------------------------------------------------------------- program-key

def test_program_key_positive(tmp_path, capsys):
    root = build(tmp_path, {"seldon_core_tpu/executor/generation.py": """\
        import jax

        class GenerativeModel:
            def __init__(self):
                self._program_config = (self.top_k,)

                def _decode(x):
                    return x[: self.window] * self.top_k

                self._decode_fn = jax.jit(_decode)
        """})
    assert run(root, "--rules", "program-key", "--no-baseline") == 1
    out = capsys.readouterr().out
    assert "self.window" in out and "top_k" not in out.replace(
        "self.top_k", ""
    )


def test_program_key_negative(tmp_path):
    root = build(tmp_path, {"seldon_core_tpu/executor/generation.py": """\
        import jax

        class GenerativeModel:
            def __init__(self):
                self._program_config = (self.top_k, self.window)

                def _decode(x):
                    return x[: self.window] * self.top_k

                self._decode_fn = jax.jit(_decode)
        """})
    assert run(root, "--rules", "program-key", "--no-baseline") == 0


def test_program_key_env_read_in_factory(tmp_path, capsys):
    root = build(tmp_path, {"seldon_core_tpu/executor/generation.py": """\
        import jax
        import os

        class GenerativeModel:
            def __init__(self):
                self._program_config = (self.top_k,)

                def _decode(x):
                    return x * int(os.environ.get("SCT_K", "1"))

                self._decode_fn = jax.jit(_decode)
        """})
    assert run(root, "--rules", "program-key", "--no-baseline") == 1
    assert "environment at trace time" in capsys.readouterr().out


def test_program_key_free_var_chased_to_attr(tmp_path, capsys):
    root = build(tmp_path, {"seldon_core_tpu/executor/generation.py": """\
        import jax

        class GenerativeModel:
            def __init__(self):
                self._program_config = (self.top_k,)
                rank = self.lora_rank or 0

                def _decode(x):
                    return x * rank

                self._decode_fn = jax.jit(_decode)
        """})
    assert run(root, "--rules", "program-key", "--no-baseline") == 1
    assert "via local 'rank'" in capsys.readouterr().out


def test_program_key_suppressed(tmp_path):
    root = build(tmp_path, {"seldon_core_tpu/executor/generation.py": """\
        import jax

        class GenerativeModel:
            def __init__(self):
                self._program_config = (self.top_k,)

                def _decode(x):
                    # sct: program-key-ok shape-only, cannot change trace
                    return x[: self.window]

                self._decode_fn = jax.jit(_decode)
        """})
    assert run(root, "--rules", "program-key", "--no-baseline") == 0


# ------------------------------------------------------------------ pairing

def test_pairing_missing_release(tmp_path, capsys):
    root = build(tmp_path, {"seldon_core_tpu/engine/pool.py": """\
        class Handler:
            def grab(self, name):
                idx = self.lora_pool.acquire(name)
                return idx
        """})
    assert run(root, "--rules", "pairing", "--no-baseline") == 1
    assert "no matching .release_ref()" in capsys.readouterr().out


def test_pairing_negative_paired(tmp_path):
    root = build(tmp_path, {"seldon_core_tpu/engine/pool.py": """\
        class Handler:
            def use(self, name):
                idx = self.lora_pool.acquire(name)
                try:
                    return self.work(idx)
                finally:
                    self.lora_pool.release_ref(idx)
        """})
    assert run(root, "--rules", "pairing", "--no-baseline") == 0


def test_pairing_unprotected_release(tmp_path, capsys):
    root = build(tmp_path, {"seldon_core_tpu/engine/pool.py": """\
        class Handler:
            def use(self, name, budget):
                self.memory.reserve(name, {"kv": budget})
                if budget > self.limit:
                    raise ValueError(budget)
                self.work(name)
                self.memory.release(name)
        """})
    assert run(root, "--rules", "pairing", "--no-baseline") == 1
    assert "can be skipped by the raise/return" in capsys.readouterr().out


def test_pairing_raise_in_acquire_guard_is_not_a_leak(tmp_path):
    # a raise inside the except handler wrapping the acquire itself
    # means the acquire failed: nothing is held, nothing leaks
    root = build(tmp_path, {"seldon_core_tpu/engine/pool.py": """\
        class Handler:
            def use(self, name):
                try:
                    idx = self.lora_pool.acquire(name)
                except KeyError as e:
                    raise ValueError(name) from e
                self.work(idx)
                self.lora_pool.release_ref(idx)
        """})
    assert run(root, "--rules", "pairing", "--no-baseline") == 0


def test_pairing_ownership_transfer_annotation(tmp_path):
    root = build(tmp_path, {"seldon_core_tpu/engine/pool.py": """\
        class Handler:
            def grab(self, name):
                # sct: pairing-ok released by drop() at request end
                idx = self.lora_pool.acquire(name)
                return idx
        """})
    assert run(root, "--rules", "pairing", "--no-baseline") == 0


def test_pairing_lock_acquire_not_matched(tmp_path):
    root = build(tmp_path, {"seldon_core_tpu/engine/pool.py": """\
        class Handler:
            def work(self):
                self._lock.acquire()
                return 1
        """})
    assert run(root, "--rules", "pairing", "--no-baseline") == 0


# ------------------------------------------------------------- env-registry

ENV_FILES = {
    "seldon_core_tpu/runtime/settings.py": """\
        REGISTRY = {"SCT_GOOD": None}

        def markdown_table():
            return "| table |"
        """,
    "docs/CONFIG.md": "| table |\n",
}


def test_env_registry_undeclared_literal(tmp_path, capsys):
    root = build(tmp_path, dict(ENV_FILES))
    build(root, {"seldon_core_tpu/mod.py": """\
        import os
        X = os.environ.get("SCT_BOGUS", "")
        """})
    assert run(root, "--rules", "env-registry", "--no-baseline") == 1
    assert "SCT_BOGUS" in capsys.readouterr().out


def test_env_registry_undeclared_docs_reference(tmp_path, capsys):
    root = build(tmp_path, dict(ENV_FILES))
    build(root, {"docs/OPS.md": "Set SCT_NOPE=1 to enable.\n"})
    assert run(root, "--rules", "env-registry", "--no-baseline") == 1
    assert "SCT_NOPE" in capsys.readouterr().out


def test_env_registry_clean(tmp_path):
    root = build(tmp_path, dict(ENV_FILES))
    build(root, {
        "seldon_core_tpu/mod.py": """\
            import os
            X = os.environ.get("SCT_GOOD", "")
            """,
        "docs/OPS.md": "Set SCT_GOOD=1 to enable.\n",
    })
    assert run(root, "--rules", "env-registry", "--no-baseline") == 0


def test_env_registry_stale_config_md(tmp_path, capsys):
    root = build(tmp_path, dict(ENV_FILES))
    (root / "docs" / "CONFIG.md").write_text("| hand-edited |\n")
    assert run(root, "--rules", "env-registry", "--no-baseline") == 1
    assert "docs/CONFIG.md is stale" in capsys.readouterr().out


def test_write_config_docs(tmp_path, capsys):
    root = build(tmp_path, dict(ENV_FILES))
    (root / "docs" / "CONFIG.md").unlink()
    assert run(root, "--write-config-docs") == 0
    assert (root / "docs" / "CONFIG.md").read_text() == "| table |\n"


# --------------------------------------------------------- async-discipline

def test_async_blocking_call(tmp_path, capsys):
    root = build(tmp_path, {"seldon_core_tpu/gateway/app.py": """\
        import time

        async def handler(request):
            time.sleep(0.5)
            return request
        """})
    assert run(root, "--rules", "async-discipline", "--no-baseline") == 1
    assert "time.sleep" in capsys.readouterr().out


def test_async_blocking_scope_excludes_executor(tmp_path):
    # the executor is thread-land; only the asyncio planes are scoped
    root = build(tmp_path, {"seldon_core_tpu/executor/helper.py": """\
        import time

        async def warmup():
            time.sleep(0.5)
        """})
    assert run(root, "--rules", "async-discipline", "--no-baseline") == 0


def test_fire_and_forget_create_task(tmp_path, capsys):
    root = build(tmp_path, {"seldon_core_tpu/gateway/app.py": """\
        import asyncio

        async def boot(work):
            asyncio.create_task(work())
        """})
    assert run(root, "--rules", "async-discipline", "--no-baseline") == 1
    assert "fire-and-forget" in capsys.readouterr().out


def test_dropped_task_handle(tmp_path, capsys):
    root = build(tmp_path, {"seldon_core_tpu/gateway/app.py": """\
        import asyncio

        async def boot(work):
            t = asyncio.create_task(work())
            return None
        """})
    assert run(root, "--rules", "async-discipline", "--no-baseline") == 1
    assert "never used after" in capsys.readouterr().out


def test_retained_task_is_clean(tmp_path):
    root = build(tmp_path, {"seldon_core_tpu/gateway/app.py": """\
        import asyncio

        async def boot(work):
            t = asyncio.create_task(work())
            t.add_done_callback(print)

        class App:
            def start(self, loop, work):
                self._task = loop.create_task(work())
        """})
    assert run(root, "--rules", "async-discipline", "--no-baseline") == 0


def test_async_suppressed(tmp_path):
    root = build(tmp_path, {"seldon_core_tpu/gateway/app.py": """\
        import time

        async def handler(request):
            # sct: async-discipline-ok sub-ms busy-wait in tests only
            time.sleep(0.0001)
            return request
        """})
    assert run(root, "--rules", "async-discipline", "--no-baseline") == 0


# ------------------------------------------------------------- test-hygiene

def test_hygiene_unmarked_subprocess_test(tmp_path, capsys):
    root = build(tmp_path, {"tests/test_spawn.py": """\
        import subprocess

        def test_spawns_server():
            subprocess.run(["true"])
        """})
    assert run(root, "--rules", "test-hygiene", "--no-baseline") == 1
    assert "not tier-1-safe" in capsys.readouterr().out


def test_hygiene_slow_marker_satisfies(tmp_path):
    root = build(tmp_path, {"tests/test_spawn.py": """\
        import subprocess
        import pytest

        @pytest.mark.slow
        def test_spawns_server():
            subprocess.run(["true"])
        """})
    assert run(root, "--rules", "test-hygiene", "--no-baseline") == 0


def test_hygiene_module_pytestmark_satisfies(tmp_path):
    root = build(tmp_path, {"tests/test_spawn.py": """\
        import subprocess
        import pytest

        pytestmark = pytest.mark.slow

        def test_spawns_server():
            subprocess.run(["true"])
        """})
    assert run(root, "--rules", "test-hygiene", "--no-baseline") == 0


def test_hygiene_signal_through_helper(tmp_path, capsys):
    root = build(tmp_path, {"tests/test_spawn.py": """\
        import subprocess

        def _launch():
            return subprocess.Popen(["sleep", "60"])

        def test_uses_helper():
            _launch()
        """})
    assert run(root, "--rules", "test-hygiene", "--no-baseline") == 1
    assert "_launch()" in capsys.readouterr().out


# ------------------------------------------------- engine: baseline + CLI

def test_annotation_without_reason_is_a_finding(tmp_path, capsys):
    # the reasonless marker is assembled at runtime so linting THIS
    # file does not trip over the fixture literal
    marker = "# sct: pairing-" + "ok"
    root = build(tmp_path, {"seldon_core_tpu/engine/pool.py": f"""\
        class Handler:
            def grab(self, name):
                {marker}
                idx = self.lora_pool.acquire(name)
                return idx
        """})
    assert run(root, "--rules", "pairing", "--no-baseline") == 1
    assert "[annotation]" in capsys.readouterr().out


def test_baseline_matched_finding_passes(tmp_path):
    root = build(tmp_path, {"seldon_core_tpu/engine/pool.py": """\
        class Handler:
            def grab(self, name):
                idx = self.lora_pool.acquire(name)
                return idx
        """})
    write_baseline(root, [
        ("pairing", "seldon_core_tpu/engine/pool.py",
         'idx = self.lora_pool.acquire(name)'),
    ])
    assert run(root, "--rules", "pairing") == 0


def test_stale_baseline_entry_fails(tmp_path, capsys):
    root = build(tmp_path, {"seldon_core_tpu/engine/pool.py": "X = 1\n"})
    write_baseline(root, [
        ("pairing", "seldon_core_tpu/engine/pool.py", "ghost = acquire()"),
    ])
    assert run(root, "--rules", "pairing") == 1
    assert "stale-baseline" in capsys.readouterr().out


def test_write_baseline_refuses_clean_dirs(tmp_path, capsys):
    root = build(tmp_path, {
        "seldon_core_tpu/engine/pool.py": """\
            class Handler:
                def grab(self, name):
                    idx = self.lora_pool.acquire(name)
                    return idx
            """,
        "seldon_core_tpu/executor/slots.py": """\
            class Slots:
                def grab(self, name):
                    idx = self.adapter_pool.acquire(name)
                    return idx
            """,
    })
    assert run(root, "--rules", "pairing", "--write-baseline") == 0
    data = json.loads((root / core.BASELINE_NAME).read_text())
    paths = [e["path"] for e in data["findings"]]
    assert paths == ["seldon_core_tpu/engine/pool.py"]
    assert "NOT written" in capsys.readouterr().out


def test_cli_list_rules(capsys):
    assert sctlint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in RULES:
        assert rule.id in out


def test_cli_explain(capsys):
    for rule_id in BY_ID:
        assert sctlint_main(["--explain", rule_id]) == 0
        assert rule_id in capsys.readouterr().out
    assert sctlint_main(["--explain", "no-such-rule"]) == 2


def test_cli_unknown_rule_filter(tmp_path):
    assert run(tmp_path, "--rules", "bogus") == 2


def test_cli_json_output(tmp_path, capsys):
    root = build(tmp_path, {"seldon_core_tpu/engine/pool.py": """\
        class Handler:
            def grab(self, name):
                idx = self.lora_pool.acquire(name)
                return idx
        """})
    assert run(root, "--rules", "pairing", "--no-baseline", "--json") == 1
    data = json.loads(capsys.readouterr().out)
    assert data["new"] and data["new"][0]["rule"] == "pairing"


# ----------------------------------------------------- repo meta-invariants

def test_repo_lint_is_green():
    """The tree itself passes `make lint-check`: all six rules, the
    checked-in baseline, non-zero on anything new."""
    assert sctlint_main([]) == 0


def test_baseline_is_minimal_and_clean_dirs_are_empty():
    entries = core.load_baseline(REPO / core.BASELINE_NAME)
    for e in entries:
        assert not e["path"].startswith(core.BASELINE_CLEAN_PREFIXES), (
            f"baseline entry in must-be-clean dir: {e}"
        )
    # minimality: every entry still matches a live finding (no stale
    # debt).  sctlint_main([]) above fails on stale entries; assert the
    # property directly too so the intent survives CLI refactors
    paths = [
        REPO / "seldon_core_tpu", REPO / "tests", REPO / "docs",
        REPO / "README.md",
    ]
    ctx = core.load_sources(REPO, paths)
    report = core.run_rules(ctx, RULES, entries)
    assert report.stale_baseline == []
    assert report.bad_baseline == []


def test_registry_covers_every_quoted_literal():
    """Every quoted SCT_* literal in the package resolves in the
    registry (prefix families count via their declared root)."""
    lit = re.compile(r"""["'](SCT_[A-Z0-9_]*[A-Z0-9_])["']""")
    missing = []
    for p in sorted((REPO / "seldon_core_tpu").rglob("*.py")):
        if "sctlint" in p.parts or p.name == "settings.py":
            continue
        for name in lit.findall(p.read_text()):
            if name.rstrip("_") not in settings.REGISTRY:
                missing.append((p.name, name))
    assert not missing


def test_registry_typed_getters():
    env = {"SCT_HBM_GB": "8", "SCT_GEN_OVERLAP": "off"}
    assert settings.get_float("SCT_HBM_GB", env) == 8.0
    assert settings.get_bool("SCT_GEN_OVERLAP", env) is False
    # defaults flow through when unset
    assert settings.get_float("SCT_HBM_GB", {}) == 16.0
    assert settings.get_bool("SCT_GEN_OVERLAP", {}) is True
    with pytest.raises(KeyError):
        settings.get_raw("SCT_NOT_DECLARED", {})


def test_config_md_matches_registry():
    want = settings.markdown_table() + "\n"
    assert (REPO / "docs" / "CONFIG.md").read_text() == want
