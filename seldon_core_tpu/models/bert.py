"""BERT-base encoder classifier — the BASELINE north-star NLP model.

Tensor-parallel-friendly layout: attention projections are DenseGeneral
with explicit (heads, head_dim) output so the ``heads`` logical axis shards
over ``tp``; the FFN shards its intermediate dim.  XLA then inserts exactly
the Megatron-style all-reduces (psum after out-proj / down-proj) from the
sharding annotations alone.

Inputs are token-id batches ``(B, L) int32``; attention masks derive from
padding (token 0).
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp

from seldon_core_tpu.models.common import annotate_params


@dataclasses.dataclass(frozen=True)
class Config:
    vocab_size: int = 30522
    hidden: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn: int = 3072
    max_len: int = 512
    n_segments: int = 2
    n_classes: int = 2
    pad_id: int = 0

    @property
    def head_dim(self) -> int:
        return self.hidden // self.n_heads


class SelfAttention(nn.Module):
    cfg: Config

    @nn.compact
    def __call__(self, x, mask):
        c = self.cfg
        proj = lambda name: nn.DenseGeneral(  # noqa: E731
            (c.n_heads, c.head_dim), axis=-1, name=name
        )
        q = proj("query")(x)
        k = proj("key")(x)
        v = proj("value")(x)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(c.head_dim).astype(x.dtype)
        scores = jnp.where(mask[:, None, None, :], scores, jnp.finfo(x.dtype).min)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return nn.DenseGeneral(c.hidden, axis=(-2, -1), name="out")(out)


class Layer(nn.Module):
    cfg: Config

    @nn.compact
    def __call__(self, x, mask):
        c = self.cfg
        a = SelfAttention(c, name="attention")(x, mask)
        x = nn.LayerNorm(name="ln_att")(x + a)
        h = nn.Dense(c.ffn, name="ffn_up")(x)
        h = nn.gelu(h)
        h = nn.Dense(c.hidden, name="ffn_down")(h)
        return nn.LayerNorm(name="ln_ffn")(x + h)


class Bert(nn.Module):
    cfg: Config

    @nn.compact
    def __call__(self, token_ids, segment_ids=None):
        c = self.cfg
        token_ids = token_ids.astype(jnp.int32)
        mask = token_ids != c.pad_id
        pos = jnp.arange(token_ids.shape[1])[None, :]
        x = nn.Embed(c.vocab_size, c.hidden, name="tok_emb")(token_ids)
        x = x + nn.Embed(c.max_len, c.hidden, name="pos_emb")(pos)
        if segment_ids is None:
            segment_ids = jnp.zeros_like(token_ids)
        x = x + nn.Embed(c.n_segments, c.hidden, name="seg_emb")(segment_ids)
        x = nn.LayerNorm(name="ln_emb")(x)
        for i in range(c.n_layers):
            x = Layer(c, name=f"layer_{i}")(x, mask)
        cls = x[:, 0]
        pooled = jnp.tanh(nn.Dense(c.hidden, name="pooler")(cls))
        return nn.softmax(nn.Dense(c.n_classes, name="head")(pooled))


def init_params(rng: jax.Array, cfg: Config = Config()):
    ids = jnp.zeros((1, 8), jnp.int32)
    return Bert(cfg).init(rng, ids)


def apply(params, batch, cfg: Config = Config()):
    return Bert(cfg).apply(params, batch)


_AXIS_RULES = [
    (r"(query|key|value)/kernel", ("embed", "heads", "head_dim")),
    (r"(query|key|value)/bias", ("heads", "head_dim")),
    (r"attention/out/kernel", ("heads", "head_dim", "embed")),
    (r"attention/out/bias", ("embed",)),
    (r"ffn_up/kernel", ("embed", "mlp")),
    (r"ffn_up/bias", ("mlp",)),
    (r"ffn_down/kernel", ("mlp", "embed")),
    (r"ffn_down/bias", ("embed",)),
    (r"tok_emb/embedding", ("vocab", "embed")),
    # position/segment tables are tiny; keep them replicated (seg table has
    # only n_segments rows — unshardable)
    (r"(pos_emb|seg_emb)/embedding", (None, "embed")),
    (r"pooler/kernel", ("embed", "embed")),
    (r"head/kernel", ("embed", None)),
]


def param_logical_axes(params):
    return annotate_params(params, _AXIS_RULES)
