"""Tiered prefix KV store: the host-DRAM tier under the HBM radix index.

The prefix working set of a busy deployment (system prompts, few-shot
headers, RAG scaffolds) dwarfs one chip's HBM.  Before this tier, a
``PrefixIndex`` eviction under pool pressure simply DROPPED blocks that
cost a full prefill to rebuild.  The :class:`HostPrefixStore` catches
them instead: evicted chain levels are device-fetched once (at an
admission sync point — never on the decode hot path) and parked in host
DRAM in the pool's own storage representation (int8 blocks + scales on a
quantized pool, raw float/bf16 otherwise — the same bytes the disagg
handoff codec ships, so a later promotion is bit-exact by construction).

On a radix match that runs past the HBM index into a demoted chain, the
model promotes the DRAM levels back with ONE donated fused scatter (the
disagg ``attach_imported`` machinery) instead of a re-prefill: prefill
device time still scales with the novel suffix only.

Keying mirrors :class:`~seldon_core_tpu.cache.prefix.PrefixIndex` — one
entry per chain level, key ``(adapter_salt, raw int32 bytes of
tokens[:k*block_size])``, so adapter-salted chains never cross and the
digest hashes match what the gateway router computes.

Demotion priority (the eviction-ordering seam this PR fixes): entries
are scored by rebuild cost — chain depth x blocks (each store entry is
one block, so its cost is its depth: rebuilding level ``k`` means
prefilling ``k * block_size`` tokens).  Under byte pressure the store
evicts the CHEAPEST chains first and never throws away a deeper chain to
make room for a shallower one, so the most-expensive-to-rebuild prefixes
survive the longest.
"""

from __future__ import annotations

import threading

import numpy as np

from seldon_core_tpu.cache.prefix import chain_hash


class _HostEntry:
    __slots__ = ("depth", "k", "v", "k_scale", "v_scale", "nbytes", "tick")

    def __init__(self, depth, k, v, k_scale, v_scale, tick):
        self.depth = int(depth)
        self.k = k
        self.v = v
        self.k_scale = k_scale
        self.v_scale = v_scale
        self.nbytes = int(
            k.nbytes + v.nbytes
            + (k_scale.nbytes if k_scale is not None else 0)
            + (v_scale.nbytes if v_scale is not None else 0)
        )
        self.tick = tick

    @property
    def cost(self) -> int:
        # rebuild cost: chain depth x block count (1 block per entry)
        return self.depth


class HostPrefixStore:
    """Byte-bounded host-DRAM tier for demoted prefix-chain KV blocks.

    Thread-safe: demotion/promotion run on the scheduler's admission
    thread while peer-pull exports read concurrently from the engine's
    request handlers.  ``on_bytes`` (when given) is called with the
    store's live byte total after every mutation — the generation plane
    wires it to the host-memory ledger (executor/memory.py,
    ``prefix_dram`` class)."""

    def __init__(
        self,
        block_size: int,
        budget_bytes: int,
        on_bytes=None,
    ):
        self.block_size = int(block_size)
        self.budget_bytes = max(0, int(budget_bytes))
        self._entries: dict[tuple, _HostEntry] = {}
        self._lock = threading.Lock()
        self._tick = 0
        self._on_bytes = on_bytes
        self.bytes = 0
        # per-tier telemetry (GET /stats/cache "tiers.dram")
        self.hits = 0  # matches that found >=1 demoted level
        self.misses = 0  # lookups that found nothing to promote
        self.promotions = 0  # levels promoted back to HBM
        self.demotions = 0  # levels absorbed from HBM evictions
        self.evictions = 0  # levels dropped under the byte bound
        self.rejected = 0  # demotions refused (would evict deeper chains)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def _note_bytes(self) -> None:
        if self._on_bytes is not None:
            self._on_bytes(self.bytes)

    @staticmethod
    def level_key(tokens: np.ndarray, k: int, block_size: int, salt: bytes) -> tuple:
        return (
            salt,
            np.ascontiguousarray(
                np.asarray(tokens, np.int32).ravel()[: k * block_size]
            ).tobytes(),
        )

    # -- demotion (HBM -> DRAM) ------------------------------------------

    def put(
        self,
        key: tuple,
        depth: int,
        k: np.ndarray,
        v: np.ndarray,
        k_scale: "np.ndarray | None" = None,
        v_scale: "np.ndarray | None" = None,
    ) -> bool:
        """Absorb one evicted chain level.  Returns False when the entry
        cannot fit: bigger than the whole budget, or room could only be
        made by evicting chains MORE expensive to rebuild (a shallow
        chain never displaces a deep one)."""
        entry = _HostEntry(depth, k, v, k_scale, v_scale, 0)
        with self._lock:
            self._tick += 1
            entry.tick = self._tick
            if entry.nbytes > self.budget_bytes:
                self.rejected += 1
                return False
            prior = self._entries.pop(key, None)
            if prior is not None:
                self.bytes -= prior.nbytes
            need = self.bytes + entry.nbytes - self.budget_bytes
            if need > 0 and not self._evict_locked(need, max_cost=entry.cost):
                self.rejected += 1
                if prior is not None:  # keep what we had
                    self._entries[key] = prior
                    self.bytes += prior.nbytes
                return False
            self._entries[key] = entry
            self.bytes += entry.nbytes
            self.demotions += 1
            self._note_bytes()
            return True

    def _evict_locked(self, need_bytes: int, max_cost: "int | None" = None) -> bool:
        """Free ``need_bytes`` by dropping the cheapest-to-rebuild CHAINS
        first.  A candidate's score is the rebuild cost of everything its
        eviction dooms — chain depth x block count over the entry plus
        every level that EXTENDS it (so a chain never strands an
        unreachable tail, and a cheap root never smuggles out an
        expensive chain: the tail's cost is in the score).  With
        ``max_cost``, victim sets scoring above it are untouchable;
        returns False (nothing evicted) when the need cannot be covered
        without them."""
        if need_bytes <= 0:
            return True
        scored = []
        for key, e in self._entries.items():
            exts = [
                kk for kk in self._entries
                if kk != key and kk[0] == key[0] and kk[1].startswith(key[1])
            ]
            chain_depth = max(
                [e.depth] + [self._entries[kk].depth for kk in exts]
            )
            scored.append((chain_depth * (1 + len(exts)), e.tick, key, exts))
        doomed: list[tuple] = []
        covered = 0
        seen: set = set()
        for cost, _tick, key, exts in sorted(
            scored, key=lambda s: (s[0], s[1], s[2])
        ):
            if covered >= need_bytes:
                break
            if max_cost is not None and cost > max_cost:
                break
            if key in seen:
                continue
            for kk in (key, *exts):
                if kk in seen:
                    continue
                seen.add(kk)
                doomed.append(kk)
                covered += self._entries[kk].nbytes
        if covered < need_bytes:
            return False
        for kk in doomed:
            self.bytes -= self._entries.pop(kk).nbytes
        self.evictions += len(doomed)
        self._note_bytes()
        return True

    # -- lookup / promotion (DRAM -> HBM) --------------------------------

    def match(
        self,
        tokens: np.ndarray,
        start_level: int,
        stop_level: int,
        salt: bytes = b"",
    ) -> list[tuple]:
        """Contiguous demoted chain levels ``start_level..stop_level`` for
        ``tokens`` — ``[(key, depth, k, v, k_scale, v_scale), ...]``.
        Entries are NOT removed (call :meth:`drop` once the promotion
        scatter lands); the arrays are the stored ones, safe to read
        because entries are immutable once put."""
        tokens = np.asarray(tokens, np.int32).ravel()
        out: list[tuple] = []
        with self._lock:
            self._tick += 1
            for lvl in range(int(start_level), int(stop_level) + 1):
                e = self._entries.get(
                    self.level_key(tokens, lvl, self.block_size, salt)
                )
                if e is None:
                    break
                e.tick = self._tick
                out.append(
                    (
                        self.level_key(tokens, lvl, self.block_size, salt),
                        e.depth, e.k, e.v, e.k_scale, e.v_scale,
                    )
                )
            if out:
                self.hits += 1
            else:
                self.misses += 1
        return out

    def peek_depth(
        self,
        tokens: np.ndarray,
        start_level: int,
        stop_level: int,
        salt: bytes = b"",
    ) -> int:
        """Deepest contiguous demoted level in ``start_level..stop_level``
        (0 when ``start_level`` itself is absent).  A pure probe — no
        hit/miss counters, no LRU ticks — used by the peer-pull client to
        decide whether a pull would gain anything."""
        tokens = np.asarray(tokens, np.int32).ravel()
        depth = int(start_level) - 1
        with self._lock:
            for lvl in range(int(start_level), int(stop_level) + 1):
                if (
                    self.level_key(tokens, lvl, self.block_size, salt)
                    not in self._entries
                ):
                    break
                depth = lvl
        return max(0, depth) if depth >= int(start_level) else 0

    def drop(self, keys) -> None:
        """Remove promoted levels (their KV now lives in HBM again)."""
        with self._lock:
            n = 0
            for key in keys:
                e = self._entries.pop(key, None)
                if e is not None:
                    self.bytes -= e.nbytes
                    n += 1
            self.promotions += n
            if n:
                self._note_bytes()

    def flush(self) -> None:
        with self._lock:
            self._entries.clear()
            self.bytes = 0
            self._note_bytes()

    # -- gossip / telemetry ----------------------------------------------

    def digest(self, max_entries: int = 4096) -> dict:
        """Routing digest of the DRAM-held chains — same hash scheme as
        ``PrefixIndex.digest`` so the gateway's ``RouterPoller`` merges
        both tiers into one per-replica chain set (a replica holding a
        chain in DRAM can still serve it warm via one promotion
        scatter)."""
        with self._lock:
            items = sorted(
                self._entries.items(), key=lambda kv: -kv[1].depth
            )[: max(0, int(max_entries))]
            return {
                "block_size": self.block_size,
                "entries": len(self._entries),
                "truncated": len(self._entries) > len(items),
                "hashes": [chain_hash(k[0] + k[1]) for k, _ in items],
                "depths": [e.depth for _, e in items],
            }

    def snapshot(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "budget_bytes": self.budget_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / total, 4) if total else None,
                "promotions": self.promotions,
                "demotions": self.demotions,
                "evictions": self.evictions,
                "rejected": self.rejected,
            }


class SuspendStore:
    """Byte-bounded host-DRAM store of whole-slot suspend records — the
    :class:`HostPrefixStore` machinery generalized from prefix-chain
    levels to entire preempted generations (docs/PACKING.md).

    Each record is ONE encoded disagg handoff frame (codec v4: prompt +
    emitted tokens, the carry token, generation options, and the slot's
    paged-KV blocks — int8 blocks + scales verbatim on a quantized pool),
    so a later resume rides the donated fused-scatter import path and is
    bit-exact by construction.

    Unlike the prefix tier this store NEVER evicts: a record is a live
    generation's only copy of its KV, so dropping one would kill the
    request.  An over-budget ``put`` is rejected instead and the caller
    leaves that slot resident (best-effort preemption).  ``on_bytes``
    mirrors the prefix store's ledger callback — the generation plane
    wires it to the host-memory ledger's ``suspend_dram`` class."""

    def __init__(self, budget_bytes: int, on_bytes=None):
        self.budget_bytes = max(0, int(budget_bytes))
        self._frames: dict = {}
        self._lock = threading.Lock()
        self._on_bytes = on_bytes
        self.bytes = 0
        # telemetry (GET /stats/breakdown "packing" / scheduler snapshot)
        self.puts = 0
        self.takes = 0
        self.rejected = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._frames)

    def _note_bytes(self) -> None:
        if self._on_bytes is not None:
            self._on_bytes(self.bytes)

    def put(self, key, frame: bytes) -> bool:
        """Park one suspend record.  False when it cannot fit (the caller
        keeps that slot running rather than lose the generation)."""
        n = len(frame)
        with self._lock:
            if self.bytes + n > self.budget_bytes or key in self._frames:
                self.rejected += 1
                return False
            self._frames[key] = frame
            self.bytes += n
            self.puts += 1
            self._note_bytes()
            return True

    def take(self, key) -> "bytes | None":
        """Pop one record for resume (or for discard when its request was
        cancelled/expired while suspended)."""
        with self._lock:
            frame = self._frames.pop(key, None)
            if frame is not None:
                self.bytes -= len(frame)
                self.takes += 1
                self._note_bytes()
            return frame

    def flush(self) -> None:
        with self._lock:
            self._frames.clear()
            self.bytes = 0
            self._note_bytes()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "records": len(self._frames),
                "bytes": self.bytes,
                "budget_bytes": self.budget_bytes,
                "puts": self.puts,
                "takes": self.takes,
                "rejected": self.rejected,
            }
