"""Sharded-parameter checkpointing for compiled models.

The reference's only persistence is pickling wrapper objects to Redis
(reference: wrappers/python/persistence.py:24-58) — adequate for bandit
counters, useless for multi-GB sharded params.  This module is the
TPU-native counterpart (SURVEY §5 "checkpoint/resume"): save/load a whole
param pytree as one atomic artifact, gathering sharded ``jax.Array`` leaves
from device and re-sharding on load onto any mesh — the serving-side
equivalent of an Orbax param checkpoint, with zero extra dependencies.

Format: a single ``.npz`` holding ``arr_0..arr_N`` plus a JSON-encoded
container skeleton (the pytree with leaves replaced by ``None``; dicts,
lists, tuples and flax FrozenDicts are supported — no pickle, so loading a
checkpoint from an untrusted source cannot execute code) and a dtype
manifest.  bfloat16 is stored as its uint16 bit pattern (numpy can't
serialize it natively) — the same framing the disagg handoff codec and the
chip-packing suspend records use (``disagg/handoff.py``, docs/PACKING.md),
so every persistence plane in the repo round-trips bf16 bit-exactly.
Writes are atomic (tmp + rename).

Multi-host note: ``jax.device_get`` gathers only addressable shards; on a
multi-host slice each host must save to a shared filesystem from process 0
(``save_params(..., only_process_zero=True)``) after a
``jax.experimental.multihost_utils`` gather — scaffolding for that lives in
``parallel/distributed.py``.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any

import jax
import numpy as np

from seldon_core_tpu.parallel.sharding import (
    DEFAULT_RULES,
    ShardingRules,
    shard_params,
)

_SKELETON_KEY = "__skeleton__"
_MANIFEST_KEY = "__manifest__"
_FORMAT_VERSION = 2


def _is_none(x: Any) -> bool:
    return x is None


def _encode_skeleton(node: Any) -> Any:
    """Pytree container structure → JSON-safe value.  ``None`` marks a leaf
    slot.  Pickle is deliberately avoided: a checkpoint must never be able to
    execute code at load time."""
    if node is None:
        return None
    if isinstance(node, dict) and type(node) is dict:
        # str(k) coercion would silently corrupt int-keyed trees at load
        # time (params[0] -> params["0"]); fail at SAVE time instead
        bad = [k for k in node if not isinstance(k, str)]
        if bad:
            raise TypeError(
                f"checkpoint dict keys must be str; got {bad[:3]!r} — "
                "JSON skeletons cannot round-trip non-string keys"
            )
        return {"t": "dict", "items": {k: _encode_skeleton(v) for k, v in node.items()}}
    if isinstance(node, tuple):
        if hasattr(node, "_fields"):  # namedtuple: would flatten to tuple
            raise TypeError(
                f"checkpoint skeleton contains namedtuple {type(node).__name__}; "
                "convert to dict/tuple before save_params (a JSON skeleton "
                "cannot reconstruct the class)"
            )
        return {"t": "tuple", "items": [_encode_skeleton(v) for v in node]}
    if isinstance(node, list):
        return {"t": "list", "items": [_encode_skeleton(v) for v in node]}
    try:
        from flax.core import FrozenDict

        if isinstance(node, FrozenDict):
            bad = [k for k in node.keys() if not isinstance(k, str)]
            if bad:
                raise TypeError(
                    f"checkpoint FrozenDict keys must be str; got {bad[:3]!r}"
                )
            return {
                "t": "frozendict",
                "items": {k: _encode_skeleton(v) for k, v in node.items()},
            }
    except ImportError:
        pass
    raise TypeError(
        f"checkpoint skeleton contains unsupported container {type(node)!r}; "
        "supported: dict, list, tuple, flax FrozenDict"
    )


def _decode_skeleton(node: Any) -> Any:
    if node is None:
        return None
    kind = node["t"]
    if kind == "dict":
        return {k: _decode_skeleton(v) for k, v in node["items"].items()}
    if kind == "tuple":
        return tuple(_decode_skeleton(v) for v in node["items"])
    if kind == "list":
        return [_decode_skeleton(v) for v in node["items"]]
    if kind == "frozendict":
        from flax.core import FrozenDict

        return FrozenDict(
            {k: _decode_skeleton(v) for k, v in node["items"].items()}
        )
    raise ValueError(f"unknown skeleton node kind {kind!r}")


def save_params(path: str, params: Any) -> int:
    """Write ``params`` to ``path`` (.npz); returns the number of leaves.

    Sharded leaves are gathered to host first.  The write is atomic: readers
    never observe a partial checkpoint.
    """
    # Flatten treating None as a leaf so *structural* Nones in the param tree
    # round-trip: the skeleton's placeholder Nones and real Nones must not be
    # conflated at load time (real Nones are recorded in the manifest).
    leaves, treedef = jax.tree_util.tree_flatten(params, is_leaf=_is_none)
    skeleton = jax.tree_util.tree_unflatten(treedef, [None] * len(leaves))

    arrays: dict[str, np.ndarray] = {}
    manifest: list[dict[str, Any]] = []
    for i, leaf in enumerate(leaves):
        if leaf is None:
            manifest.append({"dtype": "none"})
            continue
        arr = np.asarray(jax.device_get(leaf))
        entry: dict[str, Any] = {"dtype": arr.dtype.name}
        if arr.dtype.name == "bfloat16":
            arr = arr.view(np.uint16)
        arrays[f"arr_{i}"] = arr
        manifest.append(entry)

    arrays[_SKELETON_KEY] = np.frombuffer(
        json.dumps(_encode_skeleton(skeleton)).encode(), dtype=np.uint8
    )
    arrays[_MANIFEST_KEY] = np.frombuffer(
        json.dumps({"version": _FORMAT_VERSION, "leaves": manifest}).encode(),
        dtype=np.uint8,
    )

    out_dir = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(out_dir, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=out_dir, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return len(leaves)


def load_params(
    path: str,
    *,
    mesh: Any = None,
    param_axes: Any = None,
    rules: ShardingRules = DEFAULT_RULES,
) -> Any:
    """Read a checkpoint back into its original pytree structure.

    With ``mesh`` (+ optional ``param_axes`` logical-axis pytree) the leaves
    are placed sharded on device; otherwise host numpy arrays are returned
    (``CompiledModel`` then shards them at construction).
    """
    with np.load(path, allow_pickle=False) as z:
        skeleton = _decode_skeleton(json.loads(z[_SKELETON_KEY].tobytes().decode()))
        manifest = json.loads(z[_MANIFEST_KEY].tobytes().decode())
        if manifest.get("version") != _FORMAT_VERSION:
            raise ValueError(
                f"unsupported checkpoint version {manifest.get('version')!r}"
            )
        leaves = []
        for i, entry in enumerate(manifest["leaves"]):
            if entry["dtype"] == "none":
                leaves.append(None)
                continue
            arr = z[f"arr_{i}"]
            if entry["dtype"] == "bfloat16":
                import ml_dtypes

                arr = arr.view(ml_dtypes.bfloat16)
            leaves.append(arr)

    _, treedef = jax.tree_util.tree_flatten(skeleton, is_leaf=_is_none)
    params = jax.tree_util.tree_unflatten(treedef, leaves)
    if mesh is not None:
        if param_axes is not None:
            params = shard_params(params, mesh, param_axes, rules)
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            params = jax.device_put(params, NamedSharding(mesh, P()))
    return params
