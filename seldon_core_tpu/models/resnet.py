"""ResNet-50 for image classification serving — the BASELINE north-star
vision model (BASELINE.md: ≥10k predictions/sec on v5e-8).

Serving-mode batch norm: running statistics are part of the params
(``batch_stats`` collection) and are used directly — no mutable state inside
``jit``, so the forward pass is a pure function XLA can fuse end-to-end.
NHWC layout (TPU conv native).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp

from seldon_core_tpu.models.common import annotate_params


@dataclasses.dataclass(frozen=True)
class Config:
    stage_sizes: tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    width: int = 64
    n_classes: int = 1000
    image_size: int = 224
    channels: int = 3


class Bottleneck(nn.Module):
    features: int
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        norm = partial(nn.BatchNorm, use_running_average=True, momentum=0.9)
        residual = x
        y = nn.Conv(self.features, (1, 1), use_bias=False, name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), self.strides, use_bias=False, name="conv2")(y)
        y = norm(name="bn2")(y)
        y = nn.relu(y)
        y = nn.Conv(self.features * 4, (1, 1), use_bias=False, name="conv3")(y)
        y = norm(scale_init=nn.initializers.zeros, name="bn3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.features * 4, (1, 1), self.strides, use_bias=False, name="proj"
            )(residual)
            residual = norm(name="bn_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    cfg: Config

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        if x.ndim == 2:
            x = x.reshape((-1, c.image_size, c.image_size, c.channels))
        x = nn.Conv(c.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], use_bias=False, name="stem")(x)
        x = nn.BatchNorm(use_running_average=True, name="bn_stem")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(c.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = Bottleneck(c.width * 2**i, strides, name=f"stage{i}_block{j}")(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(c.n_classes, name="head")(x)
        return nn.softmax(x)


def init_params(rng: jax.Array, cfg: Config = Config()):
    x = jnp.zeros((1, cfg.image_size, cfg.image_size, cfg.channels), jnp.float32)
    return ResNet(cfg).init(rng, x)


# ImageNet channel statistics (RGB), for the on-device uint8 ingest path
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def apply(params, batch, cfg: Config = Config()):
    if batch.dtype == jnp.uint8:
        # raw-bytes serving path: clients ship uint8 pixels (4x smaller on
        # the wire than bf16, 8x smaller than the reference's packed doubles)
        # and normalization fuses into the jitted program on device.  Compute
        # dtype follows the params so the convs stay on the MXU's native
        # precision.
        dt = jax.tree.leaves(params)[0].dtype
        if batch.ndim == 2:  # flattened rows -> NHWC before channel stats
            batch = batch.reshape(
                (-1, cfg.image_size, cfg.image_size, cfg.channels)
            )
        x = batch.astype(jnp.float32) / 255.0
        x = (x - jnp.asarray(IMAGENET_MEAN)) / jnp.asarray(IMAGENET_STD)
        batch = x.astype(dt)
    return ResNet(cfg).apply(params, batch)


_AXIS_RULES = [
    (r"head/kernel", ("embed", "vocab")),
    (r"head/bias", ("vocab",)),
    # conv kernels: shard output channels over tp when large
    (r"conv\d/kernel|proj/kernel|stem/kernel", (None, None, None, "conv_out")),
    (r"bn.*/(scale|bias|mean|var)", ("conv_out",)),
]


def param_logical_axes(params):
    return annotate_params(params, _AXIS_RULES)
