"""Model-microservice gRPC server.

The gRPC twin of :mod:`seldon_core_tpu.runtime.server`: wraps one user
component behind the per-type services plus ``Generic`` (reference:
wrappers/python/model_microservice.py:92-125, router_microservice.py:93-125,
transformer_microservice.py:101-133).  Errors come back as a
``SeldonMessage`` with ``status.status = FAILURE`` rather than transport
errors, matching the REST surface.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any

import grpc
import numpy as np

from seldon_core_tpu.contract import (
    Payload,
    feedback_from_proto,
    payload_from_proto,
    payload_to_proto,
)
from seldon_core_tpu.graph.spec import PredictiveUnitSpec, UnitType
from seldon_core_tpu.graph.walker import LocalClient
from seldon_core_tpu.proto import prediction_pb2 as pb
from seldon_core_tpu.proto.grpc_defs import (
    SERVER_OPTIONS,
    add_service,
    bind_insecure_port,
    failure_message,
    unary_guard,
)

log = logging.getLogger(__name__)


class ComponentGrpc:
    """All unary handlers for one wrapped component."""

    def __init__(self, component: Any, name: str = "model", service_type: str = "MODEL"):
        self.component = component
        self.name = name
        self.service_type = service_type
        # shared annotation lock across both views (see runtime/server.py)
        from seldon_core_tpu.graph.walker import make_annotation_lock

        shared_lock = make_annotation_lock(component)
        self._model_client = LocalClient(
            PredictiveUnitSpec(name=name, type=UnitType.MODEL),
            component,
            tag_lock=shared_lock,
        )
        self._transformer_client = LocalClient(
            PredictiveUnitSpec(name=name, type=UnitType.TRANSFORMER),
            component,
            tag_lock=shared_lock,
        )

    # -- handlers (shared across the typed services and Generic) -----------

    @unary_guard
    async def Predict(self, request: pb.SeldonMessage, context) -> pb.SeldonMessage:
        out = await self._model_client.transform_input(payload_from_proto(request))
        return payload_to_proto(out)

    @unary_guard
    async def TransformInput(self, request: pb.SeldonMessage, context) -> pb.SeldonMessage:
        out = await self._transformer_client.transform_input(payload_from_proto(request))
        return payload_to_proto(out)

    @unary_guard
    async def TransformOutput(self, request: pb.SeldonMessage, context) -> pb.SeldonMessage:
        out = await self._transformer_client.transform_output(payload_from_proto(request))
        return payload_to_proto(out)

    @unary_guard
    async def Route(self, request: pb.SeldonMessage, context) -> pb.SeldonMessage:
        payload = payload_from_proto(request)
        branch = await self._model_client.route(payload)
        # routing returned as a 1x1 ndarray, like the reference router
        # runtime (wrappers/python/router_microservice.py:28-56)
        return payload_to_proto(payload.with_array(np.array([[branch]]), names=[]))

    @unary_guard
    async def Aggregate(self, request: pb.SeldonMessageList, context) -> pb.SeldonMessage:
        payloads = [payload_from_proto(m) for m in request.seldonMessages]
        if not payloads:
            return failure_message("seldonMessages list is empty", 400)
        return payload_to_proto(await self._model_client.aggregate(payloads))

    @unary_guard
    async def SendFeedback(self, request: pb.Feedback, context) -> pb.SeldonMessage:
        fb = feedback_from_proto(request)
        routing = None
        if fb.response is not None:
            routing = fb.response.meta.routing.get(self.name)
        await self._model_client.send_feedback(
            fb, int(routing) if routing is not None else None
        )
        return payload_to_proto(Payload())


def register(server: Any, handler: ComponentGrpc) -> None:
    """Register the per-type services + Generic on a grpcio server, from the
    same table the fast server uses (single source of truth)."""
    for service, table in _service_tables(handler).items():
        add_service(server, service, table)


def _service_tables(handler: ComponentGrpc) -> dict[str, dict[str, Any]]:
    return {
        "Model": {"Predict": handler.Predict, "SendFeedback": handler.SendFeedback},
        "Router": {"Route": handler.Route, "SendFeedback": handler.SendFeedback},
        "Transformer": {"TransformInput": handler.TransformInput},
        "OutputTransformer": {"TransformOutput": handler.TransformOutput},
        "Combiner": {"Aggregate": handler.Aggregate},
        "Generic": {
            "TransformInput": handler.Predict
            if handler.service_type == "MODEL"
            else handler.TransformInput,
            "TransformOutput": handler.TransformOutput,
            "Route": handler.Route,
            "Aggregate": handler.Aggregate,
            "SendFeedback": handler.SendFeedback,
        },
    }


async def start_grpc(
    component: Any, port: int, name: str = "model", service_type: str = "MODEL"
):
    """Start the microservice gRPC server — asyncio data plane by default
    (see engine/grpc_app.py for why), grpcio via SCT_GRPC_IMPL=grpcio."""
    from seldon_core_tpu.proto.grpc_defs import raw_handlers, use_grpcio

    handler = ComponentGrpc(component, name=name, service_type=service_type)
    if use_grpcio():
        server = grpc.aio.server(options=SERVER_OPTIONS)
        register(server, handler)
        bound = await bind_insecure_port(server, port)
        await server.start()
        server.bound_port = bound  # real port when asked for :0 (tests)
        log.info("microservice gRPC server on :%d (%s %s)", bound, name, service_type)
        return server

    from seldon_core_tpu.wire import FastGrpcServer

    paths: dict[str, Any] = {}
    for service, table in _service_tables(handler).items():
        paths.update(raw_handlers(service, table))
    server = FastGrpcServer(paths)
    bound = await server.start(port)
    server.bound_port = bound
    log.info(
        "microservice gRPC (h2 data plane) on :%d (%s %s)", bound, name, service_type
    )
    return server


def serve_grpc(component: Any, port: int, name: str = "model", service_type: str = "MODEL") -> None:
    """Blocking entry used by the microservice CLI.

    SIGTERM/SIGINT trigger a graceful stop and a *normal* interpreter exit so
    atexit hooks (the persistence final flush, runtime/persistence.py) run —
    bare ``asyncio.run`` would die in the default SIGTERM handler and lose
    up to a full persistence interval of state.
    """

    async def main() -> None:
        import signal

        server = await start_grpc(component, port, name=name, service_type=service_type)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop.set)
            except (NotImplementedError, RuntimeError):  # non-main thread
                pass
        stop_wait = asyncio.ensure_future(stop.wait())
        term_wait = asyncio.ensure_future(server.wait_for_termination())
        await asyncio.wait({stop_wait, term_wait}, return_when=asyncio.FIRST_COMPLETED)
        stop_wait.cancel()
        term_wait.cancel()
        await server.stop(grace=5)

    asyncio.run(main())
