"""SPMD step coordination across the hosts of a multi-host slice.

In multi-controller JAX every process of a slice must issue the *same*
program in the *same* order for cross-host collectives to complete — but
only the coordinator pod receives ingress traffic (engine/app.py
mesh_worker).  This module closes that gap with a broadcast-driven
follower protocol:

- every process registers the same step functions under the same keys
  (construction is deterministic from the shared graph spec, so each host
  builds identical CompiledModels);
- the coordinator serializes each step's control message (key + payload)
  and broadcasts it with ``multihost_utils.broadcast_one_to_all`` — itself
  a collective every process participates in;
- workers sit in :meth:`follower_loop`, decode each broadcast, and invoke
  the registered function with the same operands, so the jitted call's
  collectives line up across hosts;
- an idle coordinator broadcasts NOOP heartbeats so workers never sit in a
  collective long enough to hit the runtime's barrier timeout.

The reference has no analogue — no model there ever spans processes
(reference: SURVEY.md §2.7: replica Deployments behind a Service are the
only scale-out).

Wire format: a fixed 64 KiB header buffer (op + framed step metadata +
inline payload when it fits), optionally followed by a second broadcast of
the payload rounded up to 1 MiB granularity — bounded distinct shapes keep
the number of compiled broadcast programs small.

Step metadata is length-prefixed JSON + raw little-endian ndarray segments
(:func:`encode_step` / :func:`decode_step`) — the same framing discipline
``taplog.py`` uses on its wire.  The control plane deliberately carries NO
pickles: a peer that can inject into the slice's broadcast must never be
able to execute code on every host (checkpoints made the same move in
``executor/checkpoint.py``); an unregistered key or malformed frame is a
fail-fast restart, not an RCE.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
import time
from typing import Any, Callable

import numpy as np

from seldon_core_tpu import chaos

log = logging.getLogger(__name__)

HEADER_BYTES = 64 * 1024
CHUNK_BYTES = 1024 * 1024  # payload broadcasts round up to this granularity

_OP_NOOP = 0
_OP_STEP = 1
_OP_EXIT = 2

# Step frames open with a magic + version prefix so a peer built from a
# different release can never silently mis-decode a frame: the multihost
# follower loop AND the disagg KV-handoff codec (disagg/handoff.py) share
# this framing, and both treat a mismatch as fail-fast version skew rather
# than reinterpreting raw ndarray bytes under the wrong layout.  Bump
# FRAME_VERSION whenever the header JSON schema or segment layout changes.
FRAME_MAGIC = b"SCT1"
FRAME_VERSION = 1

_HDR_LEN = struct.Struct("<4sHI")  # magic, version, json header length


def encode_step(key: str, payload: dict) -> bytes:
    """Frame one SPMD step as length-prefixed JSON + raw ndarray segments.

    ``payload`` must be a flat dict whose values are JSON scalars (str /
    int / float / bool / None), lists of scalars, or numpy ndarrays —
    exactly what the step bodies ship.  Anything else raises ``TypeError``
    at the COORDINATOR (the sender), never a deserialization surprise at a
    follower.  Arrays travel as raw little-endian bytes after the header:

        <4s magic "SCT1"> <u16 version> <u32 header_len> <json header>
        <array 0 bytes> <array 1 bytes> ...

    with the header recording each array's name/dtype/shape in order.  The
    magic/version prefix makes cross-build skew (disagg pools rolled at
    different times) a fail-fast :class:`ValueError`, never a mis-decode.
    """
    if not isinstance(payload, dict):
        raise TypeError(f"step payload must be a dict, got {type(payload).__name__}")
    plain: dict[str, Any] = {}
    # (name, contiguous buffer, true shape): ascontiguousarray promotes
    # 0-d arrays to 1-d, so the shape is captured from the original
    arrays: list[tuple[str, np.ndarray, list[int]]] = []
    for k, v in payload.items():
        if isinstance(v, np.ndarray):
            arrays.append((k, np.ascontiguousarray(v), list(v.shape)))
        elif isinstance(v, np.generic):
            plain[k] = v.item()
        elif isinstance(v, (str, int, float, bool)) or v is None:
            plain[k] = v
        elif isinstance(v, (list, tuple)):
            if any(not isinstance(e, (str, int, float, bool)) and e is not None for e in v):
                raise TypeError(
                    f"step payload field {k!r}: lists may hold scalars only"
                )
            plain[k] = list(v)
        else:
            raise TypeError(
                f"step payload field {k!r} has unframeable type "
                f"{type(v).__name__} (ndarray / JSON scalar / scalar list only)"
            )
    header = json.dumps(
        {
            "key": key,
            "plain": plain,
            "arrays": [
                {"name": k, "dtype": a.dtype.str, "shape": shape}
                for k, a, shape in arrays
            ],
        },
        separators=(",", ":"),
    ).encode()
    parts = [_HDR_LEN.pack(FRAME_MAGIC, FRAME_VERSION, len(header)), header]
    parts.extend(a.tobytes() for _, a, _shape in arrays)
    return b"".join(parts)


def decode_step(buf: bytes) -> tuple[str, dict]:
    """Inverse of :func:`encode_step`; raises ``ValueError`` on a torn or
    malformed frame, a wrong magic, or a version mismatch (the follower
    loop treats any of those as fatal version skew)."""
    if len(buf) < _HDR_LEN.size:
        raise ValueError("step frame shorter than its length prefix")
    magic, version, n = _HDR_LEN.unpack_from(buf, 0)
    if magic != FRAME_MAGIC:
        raise ValueError(
            f"step frame magic {magic!r} != {FRAME_MAGIC!r} — peer speaks a "
            "different protocol (or the stream is corrupt)"
        )
    if version != FRAME_VERSION:
        raise ValueError(
            f"step frame version {version} != {FRAME_VERSION} — peer built "
            "from a different release; refusing to decode"
        )
    if len(buf) < _HDR_LEN.size + n:
        raise ValueError("step frame truncated before header end")
    header = json.loads(buf[_HDR_LEN.size : _HDR_LEN.size + n])
    payload: dict[str, Any] = dict(header["plain"])
    off = _HDR_LEN.size + n
    for d in header["arrays"]:
        dt = np.dtype(d["dtype"])
        shape = tuple(int(s) for s in d["shape"])
        nbytes = dt.itemsize * int(np.prod(shape, dtype=np.int64)) if shape else dt.itemsize
        if len(buf) < off + nbytes:
            raise ValueError(f"step frame truncated inside array {d['name']!r}")
        arr = np.frombuffer(buf, dtype=dt, count=nbytes // dt.itemsize, offset=off)
        # copy: frombuffer views are read-only and pin the whole frame alive
        payload[d["name"]] = arr.reshape(shape).copy()
        off += nbytes
    return str(header["key"]), payload


def _encode_header(op: int, meta: bytes, inline: bool) -> np.ndarray:
    buf = np.zeros(HEADER_BYTES, dtype=np.uint8)
    buf[0] = op
    buf[1] = 1 if inline else 0
    buf[2:10] = np.frombuffer(np.uint64(len(meta)).tobytes(), dtype=np.uint8)
    if inline:
        buf[10 : 10 + len(meta)] = np.frombuffer(meta, dtype=np.uint8)
    return buf


def _decode_header(buf: np.ndarray) -> tuple[int, int, bytes | None]:
    op = int(buf[0])
    inline = bool(buf[1])
    size = int(np.frombuffer(buf[2:10].tobytes(), dtype=np.uint64)[0])
    if inline:
        return op, size, buf[10 : 10 + size].tobytes()
    return op, size, None


_driver: "MultihostDriver | None" = None


def init_driver(is_coordinator: bool, heartbeat_s: float = 10.0) -> "MultihostDriver":
    """Create the process-wide driver (engine boot, right after
    jax.distributed initialization).  Idempotent."""
    global _driver
    if _driver is None:
        _driver = MultihostDriver(is_coordinator, heartbeat_s=heartbeat_s)
    return _driver


def get_driver() -> "MultihostDriver | None":
    """The process-wide driver, or None outside a multi-host slice."""
    return _driver


class MultihostDriver:
    """Lead/follow protocol for SPMD steps over a multi-host slice.

    One driver per process.  The coordinator calls :meth:`lead`; worker
    processes run :meth:`follower_loop` (usually on a daemon thread started
    by the engine boot).  ``register`` must be called identically on every
    process before the first step.
    """

    def __init__(self, is_coordinator: bool, heartbeat_s: float = 10.0):
        self.is_coordinator = is_coordinator
        self.heartbeat_s = heartbeat_s
        self._fns: dict[str, Callable[[Any], Any]] = {}
        self._lock = threading.Lock()  # serializes broadcast order
        self._stop = threading.Event()
        self._last_step = time.monotonic()
        self._hb_thread: threading.Thread | None = None

    # -- registry ----------------------------------------------------------

    def register(self, key: str, fn: Callable[[Any], Any]) -> None:
        if key in self._fns:
            raise ValueError(f"step fn {key!r} already registered")
        self._fns[key] = fn

    def register_unique(self, base: str, fn: Callable[[Any], Any]) -> str:
        """Register under ``base#<seq>`` and return the key.  Deterministic
        across processes as long as registration order is (it is: every host
        builds the same units from the same graph spec in the same order)."""
        key = f"{base}#{len(self._fns)}"
        self.register(key, fn)
        return key

    # -- broadcast plumbing ------------------------------------------------

    @staticmethod
    def _broadcast(buf: np.ndarray) -> np.ndarray:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.broadcast_one_to_all(buf))

    def _send(self, op: int, meta: bytes = b"") -> None:
        inline = len(meta) <= HEADER_BYTES - 10
        self._broadcast(_encode_header(op, meta, inline))
        if not inline:
            padded = -(-len(meta) // CHUNK_BYTES) * CHUNK_BYTES
            payload = np.zeros(padded, dtype=np.uint8)
            payload[: len(meta)] = np.frombuffer(meta, dtype=np.uint8)
            self._broadcast(payload)

    def _recv(self) -> tuple[int, bytes]:
        got = self._broadcast(np.zeros(HEADER_BYTES, dtype=np.uint8))
        op, size, meta = _decode_header(got)
        if meta is None:
            padded = -(-size // CHUNK_BYTES) * CHUNK_BYTES
            payload = self._broadcast(np.zeros(padded, dtype=np.uint8))
            meta = payload[:size].tobytes()
        return op, meta

    # -- coordinator side --------------------------------------------------

    def lead(self, key: str, payload: Any) -> Any:
        """Broadcast one step and execute it locally; returns the local
        result.  Serialized: broadcast order is the SPMD program order."""
        if not self.is_coordinator:
            raise RuntimeError("lead() called on a follower process")
        fn = self._fns[key]
        meta = encode_step(key, payload)
        with self._lock:
            if chaos.ENABLED:
                # injected BEFORE the broadcast: the slice never sees a
                # partial step, the caller sees a failed one — the
                # scheduler's fail-inflight path, not a wedged collective
                chaos.fire("mh.step")
            self._send(_OP_STEP, meta)
            self._last_step = time.monotonic()
            return fn(payload)

    def start_heartbeat(self) -> None:
        """Keep idle workers out of collective-barrier timeouts."""
        if not self.is_coordinator or self._hb_thread is not None:
            return

        def _beat() -> None:
            while not self._stop.wait(self.heartbeat_s / 2):
                with self._lock:
                    if time.monotonic() - self._last_step >= self.heartbeat_s:
                        self._send(_OP_NOOP)
                        self._last_step = time.monotonic()

        self._hb_thread = threading.Thread(target=_beat, daemon=True, name="sct-mh-heartbeat")
        self._hb_thread.start()

    def shutdown(self) -> None:
        """Coordinator: release the followers and stop the heartbeat."""
        self._stop.set()
        if self.is_coordinator:
            with self._lock:
                self._send(_OP_EXIT)
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None

    # -- worker side -------------------------------------------------------

    def follower_loop(self) -> None:
        """Execute broadcast steps until the coordinator sends EXIT.

        Runs on a (daemon) thread on worker processes — the collectives
        block, so this must not share the asyncio event loop serving
        /ping.  Any failure after a step broadcast is received is FATAL:
        the coordinator and the other workers execute the step's
        collectives regardless, so a process that skips the step (can't
        decode it, doesn't have the key — version skew) or aborts mid-step
        leaves the slice desynchronized: the peers' collective wedges until
        barrier timeout, or worse, pairs mismatched programs.  Hard-exiting
        instead lets the supervisor (kubernetes) restart the slice cleanly.
        """
        if self.is_coordinator:
            raise RuntimeError("follower_loop() called on the coordinator")
        while not self._stop.is_set():
            op, meta = self._recv()
            if op == _OP_EXIT:
                return
            if op == _OP_NOOP:
                continue
            try:
                key, payload = decode_step(meta)
                fn = self._fns[key]
            except Exception:
                log.exception(
                    "multihost follower: undecodable or unregistered step "
                    "(version skew?); peers entered its collectives without "
                    "us — terminating so the supervisor restarts the slice"
                )
                os._exit(13)
            try:
                if chaos.ENABLED:
                    # exit-kind rules kill the process outright (simulated
                    # follower death); raisable kinds land in the FATAL
                    # handler below — both end in the supervisor restart
                    # the production failure would
                    chaos.fire("mh.follower")
                fn(payload)
            except Exception:
                log.exception(
                    "multihost follower step %r failed mid-step; slice is "
                    "desynchronized — terminating so the supervisor restarts it",
                    key,
                )
                os._exit(13)
