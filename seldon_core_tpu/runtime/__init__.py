"""User-model microservice runtime (the reference's `wrappers/python`)."""

from seldon_core_tpu.runtime.server import MicroserviceApp, serve
from seldon_core_tpu.runtime.microservice import load_component

__all__ = ["MicroserviceApp", "serve", "load_component"]
