"""Reconciliation: desired state -> cluster state.

The reference's reconcile contract, kept exactly (reference:
SeldonDeploymentControllerImpl.java:260-310):

1. skip if the CR previously FAILED (parked until the spec changes,
   :263-267) or the spec is unchanged since the cached reconcile (:270-271)
2. defaulting -> validate -> cache
3. create-or-update owned Deployments; delete orphans (owned objects not in
   the desired set, selected by the seldon-deployment-id label, :209-243)
4. same for Services
5. on validation/creation failure: status.state=FAILED with description
6. push the defaulted CR back when defaulting changed the spec (:286-290)

Status writeback (replicas available per predictor) mirrors the reference's
second watcher (DeploymentWatcher.java:60-144 +
SeldonDeploymentStatusUpdateImpl.java:49-85).
"""

from __future__ import annotations

import logging
from typing import Any

from seldon_core_tpu.operator.crd import (
    LABEL_DEPLOYMENT_ID,
    DeploymentStatus,
    PredictorStatus,
    SeldonDeployment,
)
from seldon_core_tpu.operator.defaulting import ValidationError, defaulting, validate
from seldon_core_tpu.operator.kube import KubeApi, NotFound
from seldon_core_tpu.operator.names import engine_deployment_name
from seldon_core_tpu.operator.resources import ENGINE_IMAGE_DEFAULT, create_resources

log = logging.getLogger(__name__)

CR_KIND = "SeldonDeployment"


# Annotations recording what the operator last applied.  Comparing desired
# hashes against them (instead of full-JSON spec compares) makes reconciles
# immune to server-side defaulting (which would otherwise read as drift and,
# for StatefulSets, roll every slice's pods on every operator restart) while
# still catching REMOVED fields (the desired hash changes).
ANNOTATION_SPEC_HASH = "seldon.io/spec-hash"
ANNOTATION_TEMPLATE_HASH = "seldon.io/template-hash"


def _hash_of(value: Any) -> str:
    import hashlib
    import json

    return hashlib.sha256(
        json.dumps(value, sort_keys=True, default=str).encode()
    ).hexdigest()[:32]


class Controller:
    def __init__(self, kube: KubeApi, engine_image: str = ENGINE_IMAGE_DEFAULT):
        self.kube = kube
        self.engine_image = engine_image
        self._spec_cache: dict[str, str] = {}  # name -> spec signature
        self._failed: dict[str, str] = {}  # name -> failed spec signature
        # workload name -> replica count owned by the autoscale reconciler
        # (autoscale/reconciler.py).  Applied to desired workloads before
        # hashing so a CR edit re-rolls the pods WITHOUT snapping an
        # autoscaled pool back to the CR's static replica count.
        self.replica_overrides: dict[str, int] = {}

    # -- reconcile ---------------------------------------------------------

    async def reconcile(self, mldep: SeldonDeployment) -> None:
        name = mldep.metadata.name
        ns = mldep.metadata.namespace
        signature = mldep.spec_signature()

        if self._failed.get(name) == signature:
            log.debug("skipping FAILED deployment %s (spec unchanged)", name)
            return
        if self._spec_cache.get(name) == signature:
            log.debug("skipping unchanged deployment %s", name)
            return

        try:
            defaulted = defaulting(mldep)
            validate(defaulted)
            workloads, services = create_resources(defaulted, self.engine_image)
            for w in workloads:
                n = self.replica_overrides.get(w["metadata"]["name"])
                if n is not None and "replicas" in w.get("spec", {}):
                    w["spec"]["replicas"] = n
            uid = mldep.metadata.uid
            for kind in ("Deployment", "StatefulSet"):
                await self._apply(
                    ns,
                    name,
                    kind,
                    [w for w in workloads if w["kind"] == kind],
                    owner_uid=uid,
                )
            await self._apply(ns, name, "Service", services, owner_uid=uid)
        except ValidationError as e:
            log.warning("deployment %s failed validation: %s", name, e)
            self._failed[name] = signature
            await self._write_status(
                mldep, DeploymentStatus(state="FAILED", description=str(e))
            )
            return
        except Exception as e:
            # transient (API hiccup, conflict, network): surface in status
            # but do NOT park — the next event or resync retries; only
            # validation failures park (reference parks everything,
            # :263-267, which is a known scar)
            log.exception("reconcile of %s failed; will retry", name)
            await self._write_status(
                mldep,
                DeploymentStatus(state="Creating", description=f"retrying: {type(e).__name__}: {e}"),
            )
            return

        self._failed.pop(name, None)
        self._spec_cache[name] = signature
        # push the defaulted spec back when defaulting changed it
        if defaulted.spec_signature() != signature:
            defaulted.status = mldep.status
            try:
                await self.kube.update(CR_KIND, ns, defaulted.to_dict())
                self._spec_cache[name] = defaulted.spec_signature()
            except NotFound:
                pass
        await self._refresh_status(defaulted)

    async def _apply(
        self,
        ns: str,
        owner: str,
        kind: str,
        desired: list[dict[str, Any]],
        owner_uid: str = "",
    ) -> None:
        desired_names = {d["metadata"]["name"] for d in desired}
        for obj in desired:
            obj["metadata"].setdefault("labels", {})[LABEL_DEPLOYMENT_ID] = owner
            annotations = obj["metadata"].setdefault("annotations", {})
            annotations[ANNOTATION_SPEC_HASH] = _hash_of(obj.get("spec"))
            template = obj.get("spec", {}).get("template")
            if template is not None:
                annotations[ANNOTATION_TEMPLATE_HASH] = _hash_of(template)
            if owner_uid:
                # kube GC cleans these up even if the operator misses the
                # CR deletion (down, watch gap)
                obj["metadata"]["ownerReferences"] = [
                    {
                        "apiVersion": "machinelearning.seldon.io/v1alpha2",
                        "kind": "SeldonDeployment",
                        "name": owner,
                        "uid": owner_uid,
                        "controller": True,
                        "blockOwnerDeletion": False,
                    }
                ]
            try:
                existing = await self.kube.get(kind, ns, obj["metadata"]["name"])
            except NotFound:
                await self.kube.create(kind, ns, obj)
                continue
            existing_ann = existing.get("metadata", {}).get("annotations", {})
            if existing_ann.get(ANNOTATION_SPEC_HASH) != annotations[ANNOTATION_SPEC_HASH]:
                merged = dict(existing)
                merged["spec"] = obj["spec"]
                merged["metadata"] = {
                    **existing.get("metadata", {}),
                    **obj["metadata"],
                    "annotations": {**existing_ann, **annotations},
                }
                await self.kube.update(kind, ns, merged)
                # whole-slice restart ONLY for pod-template changes: a
                # replicas-only scale keeps healthy slice pods running
                # (OnDelete creates the new ordinals without a roll)
                if kind == "StatefulSet" and existing_ann.get(
                    ANNOTATION_TEMPLATE_HASH
                ) != annotations.get(ANNOTATION_TEMPLATE_HASH):
                    await self._roll_statefulset(ns, merged)
        # orphan GC: owned objects no longer desired
        owned = await self.kube.list(kind, ns, {LABEL_DEPLOYMENT_ID: owner})
        for obj in owned:
            if obj["metadata"]["name"] not in desired_names:
                try:
                    await self.kube.delete(kind, ns, obj["metadata"]["name"])
                except NotFound:
                    pass

    async def _roll_statefulset(self, ns: str, sts: dict[str, Any]) -> None:
        """Multi-host slices use updateStrategy OnDelete (worker pods never
        go Ready, so RollingUpdate would wedge on the first worker, and a
        slice's compiled programs must match across hosts anyway): restart
        the whole slice by deleting its pods; the StatefulSet recreates them
        in parallel from the new template."""
        selector = sts.get("spec", {}).get("selector", {}).get("matchLabels", {})
        if not selector:
            return
        for pod in await self.kube.list("Pod", ns, selector):
            try:
                await self.kube.delete("Pod", ns, pod["metadata"]["name"])
            except NotFound:
                pass

    # -- delete ------------------------------------------------------------

    async def delete(self, mldep: SeldonDeployment) -> None:
        """CR deleted: remove every owned object (the reference leans on
        ownerReferences GC; the fake has no GC, so deletion is explicit)."""
        name = mldep.metadata.name
        ns = mldep.metadata.namespace
        self._spec_cache.pop(name, None)
        self._failed.pop(name, None)
        for wname in [w for w in self.replica_overrides
                      if w.startswith(f"{name}-")]:
            del self.replica_overrides[wname]
        for kind in ("Deployment", "StatefulSet", "Service"):
            for obj in await self.kube.list(kind, ns, {LABEL_DEPLOYMENT_ID: name}):
                try:
                    await self.kube.delete(kind, ns, obj["metadata"]["name"])
                except NotFound:
                    pass

    # -- status ------------------------------------------------------------

    async def _write_status(self, mldep: SeldonDeployment, status: DeploymentStatus) -> None:
        try:
            await self.kube.update_status(
                CR_KIND, mldep.metadata.namespace, mldep.metadata.name, status.model_dump()
            )
        except NotFound:
            pass

    async def _refresh_status(self, mldep: SeldonDeployment) -> None:
        """Recompute predictorStatus from owned engine Deployments."""
        ns = mldep.metadata.namespace
        statuses = []
        available_all = True
        for predictor in mldep.spec.predictors:
            eng = engine_deployment_name(mldep.metadata.name, predictor.name)
            obj = None
            for kind in ("Deployment", "StatefulSet"):  # multi-host engines are StatefulSets
                try:
                    obj = await self.kube.get(kind, ns, eng)
                    break
                except NotFound:
                    continue
            if obj is None:
                available_all = False
                statuses.append(PredictorStatus(name=predictor.name, replicas=predictor.replicas))
                continue
            status = obj.get("status", {})
            avail = int(
                status.get("availableReplicas", status.get("readyReplicas", 0)) or 0
            )
            if obj.get("kind") == "StatefulSet":
                # multi-host slice: only the per-slice coordinator pod ever
                # reports /ready (workers stay 503 to keep themselves out of
                # the ingress Service), and the coordinator cannot become
                # ready until jax.distributed.initialize has connected every
                # host — so "one ready pod per slice replica" == "slice up"
                want = predictor.replicas
            else:
                want = int(obj.get("spec", {}).get("replicas", predictor.replicas))
            statuses.append(
                PredictorStatus(
                    name=predictor.name,
                    replicas=want,
                    replicasAvailable=avail,
                )
            )
            if avail < want:
                available_all = False
        await self._write_status(
            mldep,
            DeploymentStatus(
                state="Available" if available_all else "Creating",
                predictorStatus=statuses,
            ),
        )

    async def sweep_orphans(self, namespace: str) -> int:
        """Delete owned objects whose CR no longer exists — covers deletions
        missed while the operator was down (ownerReferences also cover this
        on a real cluster; the sweep makes it deterministic and testable)."""
        live = {
            cr["metadata"]["name"] for cr in await self.kube.list(CR_KIND, namespace)
        }
        removed = 0
        for kind in ("Deployment", "StatefulSet", "Service"):
            for obj in await self.kube.list(kind, namespace):
                owner = obj.get("metadata", {}).get("labels", {}).get(LABEL_DEPLOYMENT_ID)
                if owner and owner not in live:
                    try:
                        await self.kube.delete(kind, namespace, obj["metadata"]["name"])
                        removed += 1
                    except NotFound:
                        pass
        return removed

    async def on_deployment_event(self, obj: dict[str, Any]) -> None:
        """A k8s Deployment changed: refresh the owning CR's status
        (the reference's DeploymentWatcher feed)."""
        owner = obj.get("metadata", {}).get("labels", {}).get(LABEL_DEPLOYMENT_ID)
        if not owner:
            return
        ns = obj.get("metadata", {}).get("namespace", "default")
        try:
            raw = await self.kube.get(CR_KIND, ns, owner)
        except NotFound:
            return
        await self._refresh_status(SeldonDeployment.from_dict(raw))
