"""sct-release (tools/release.py): version stamping + changelog — the
reference's release.py / create-changelog as a tested tool."""

import os
import subprocess
import sys

import pytest

from seldon_core_tpu.tools import release

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestVersionSurfaces:
    def test_surfaces_agree(self):
        versions = release.read_versions(REPO_ROOT)
        assert len(set(versions.values())) == 1, versions

    def test_rendered_images_carry_the_version(self):
        import seldon_core_tpu
        from seldon_core_tpu.operator.install import (
            GATEWAY_IMAGE,
            OPERATOR_IMAGE,
            TAP_IMAGE,
        )
        from seldon_core_tpu.operator.resources import ENGINE_IMAGE_DEFAULT

        v = seldon_core_tpu.__version__
        for image in (OPERATOR_IMAGE, GATEWAY_IMAGE, TAP_IMAGE, ENGINE_IMAGE_DEFAULT):
            assert image.endswith(f":{v}"), image
        # and the rendered manifests (goldens re-render on stamp)
        rendered = open(os.path.join(REPO_ROOT, "deploy", "install.yaml")).read()
        assert f":{v}" in rendered
        assert ":latest" not in rendered

    def test_bad_version_rejected(self):
        with pytest.raises(SystemExit):
            release.set_version("not-a-version", REPO_ROOT)


class TestChangelog:
    def test_changelog_groups_commits(self):
        text = release.changelog(REPO_ROOT)
        assert text.startswith("# Changelog")
        assert "## Unreleased" in text
        assert text.count("- ") >= 5  # this repo has history


class TestStampRoundTrip:
    def test_set_version_stamps_a_copy(self, tmp_path):
        """Stamp a scratch copy of the two surfaces + verify; never touches
        the real tree."""
        root = tmp_path
        (root / "seldon_core_tpu").mkdir()
        (root / "pyproject.toml").write_text('name = "x"\nversion = "0.1.0"\n')
        (root / "seldon_core_tpu" / "__init__.py").write_text(
            '__version__ = "0.1.0"\n'
        )
        # patch out the manifest re-render (scratch tree has no renderer)
        orig = subprocess.run
        try:
            subprocess.run = lambda *a, **k: None  # type: ignore[assignment]
            touched = release.set_version("0.2.0", str(root))
        finally:
            subprocess.run = orig
        assert "pyproject.toml" in touched
        assert 'version = "0.2.0"' in (root / "pyproject.toml").read_text()
        assert '__version__ = "0.2.0"' in (
            root / "seldon_core_tpu" / "__init__.py"
        ).read_text()
