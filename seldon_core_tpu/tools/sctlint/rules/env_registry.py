"""env-registry: every ``SCT_*`` env var is declared exactly once.

``seldon_core_tpu/runtime/settings.py`` is the single source of truth
for the serving plane's env knobs (name, default, type, one-line doc).
This rule holds three edges of that contract:

* every quoted ``SCT_*`` literal in package code must be a declared
  name (or a declared prefix — the QoS controller composes
  ``{prefix}_{KNOB}`` names from ``SCT_QOS``/``SCT_GW_QOS``);
* every ``SCT_*`` token a docs page or README mentions must be
  declared — stale knob references rot fastest in docs;
* ``docs/CONFIG.md`` must byte-match the generated table
  (``python -m seldon_core_tpu.tools.sctlint --write-config-docs``).

The registry module is loaded by file path (stdlib-only, jax-free), so
the rule sees the post-expansion table, not just literal declare()
calls.
"""

from __future__ import annotations

import importlib.util
import re
import sys
from pathlib import Path
from typing import Iterable

from seldon_core_tpu.tools.sctlint.core import Context, Finding, Rule

TOKEN_RE = re.compile(r"SCT_[A-Z0-9_]*[A-Z0-9]")
LITERAL_RE = re.compile(r"""["']
    (SCT_[A-Z0-9_]*[A-Z0-9_])
    ["']""", re.X)

CONFIG_DOC = "docs/CONFIG.md"


def load_registry(root: Path) -> dict:
    """The live registry, imported standalone so no package __init__
    (and no jax) is touched."""
    path = root / "seldon_core_tpu" / "runtime" / "settings.py"
    spec = importlib.util.spec_from_file_location("_sct_settings", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves sys.modules[cls.__module__] at class-creation
    # time, so the module must be registered before exec
    sys.modules["_sct_settings"] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop("_sct_settings", None)
    return mod.REGISTRY, mod


def _declared(name: str, registry: dict) -> bool:
    # prefix family roots (SCT_QOS, SCT_GW_QOS) are declared as entries
    # themselves, so wildcard references like "SCT_QOS_*" (the token
    # regex stops at the root) and composed literals like "SCT_QOS_"
    # both resolve through a plain lookup
    return name.rstrip("_") in registry


def check(ctx: Context) -> Iterable[Finding]:
    try:
        registry, mod = load_registry(ctx.root)
    except (OSError, AttributeError, ImportError) as e:
        return [Finding(
            "env-registry", "seldon_core_tpu/runtime/settings.py", 1,
            f"cannot load the settings registry: {e}", "",
        )]
    out: list[Finding] = []

    for src in ctx.py:
        if not src.rel.startswith("seldon_core_tpu/"):
            continue
        if src.rel.endswith("runtime/settings.py") \
                or "/tools/sctlint/" in src.rel:
            continue
        for i, line in enumerate(src.lines, 1):
            for m in LITERAL_RE.finditer(line):
                name = m.group(1)
                if not _declared(name, registry):
                    out.append(Finding(
                        "env-registry", src.rel, i,
                        f"env var {name} is not declared in "
                        "runtime/settings.py — declare() it with a "
                        "default and one-line doc",
                        src.snippet(i),
                    ))

    for src in ctx.docs:
        if not src.rel.endswith(".md"):
            continue
        for i, line in enumerate(src.lines, 1):
            for m in TOKEN_RE.finditer(line):
                name = m.group(0)
                if not _declared(name, registry):
                    out.append(Finding(
                        "env-registry", src.rel, i,
                        f"docs reference {name}, which is not declared "
                        "in runtime/settings.py — fix the reference or "
                        "declare the var",
                        src.snippet(i),
                    ))

    cfg = ctx.root / CONFIG_DOC
    want = mod.markdown_table() + "\n"
    have = cfg.read_text() if cfg.is_file() else ""
    if have != want:
        out.append(Finding(
            "env-registry", CONFIG_DOC, 1,
            "docs/CONFIG.md is stale — regenerate with "
            "`python -m seldon_core_tpu.tools.sctlint "
            "--write-config-docs`",
            "(generated file drift)",
        ))
    return out


RULE = Rule(
    id="env-registry",
    summary="SCT_* env vars declared centrally; docs reference only "
            "declared vars",
    explain=__doc__,
    check=check,
)
