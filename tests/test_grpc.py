"""gRPC data-plane tests: proto round-trips through real grpc.aio servers —
microservice services, engine Seldon service, and the engine->unit gRPC
transport (mirrors the reference's FakeEngineServer pattern,
api-frontend/src/test/java/io/seldon/apife/grpc/FakeEngineServer.java:86-103,
but with live in-process servers)."""

import asyncio

import grpc
import numpy as np
import pytest

from seldon_core_tpu.contract import Payload, payload_from_proto, payload_to_proto
from seldon_core_tpu.engine.grpc_app import start_engine_grpc
from seldon_core_tpu.engine.service import PredictionService
from seldon_core_tpu.graph.spec import PredictorSpec
from seldon_core_tpu.proto import prediction_pb2 as pb
from seldon_core_tpu.proto.grpc_defs import Stub
from seldon_core_tpu.runtime.grpc_service import start_grpc

run = asyncio.run


class Doubler:
    def predict(self, X, names):
        return np.asarray(X) * 2.0


class PickSecond:
    def route(self, X, names):
        return 1

    def send_feedback(self, X, names, reward, truth=None, routing=None):
        self.last = (reward, routing)


def _sm(values) -> pb.SeldonMessage:
    return payload_to_proto(Payload.from_array(np.asarray(values)))


class TestMicroserviceGrpc:
    def test_model_predict(self):
        async def go():
            server = await start_grpc(Doubler(), 0, name="d")
            async with grpc.aio.insecure_channel(f"127.0.0.1:{server.bound_port}") as ch:
                stub = Stub(ch, "Model")
                reply = await stub.Predict(_sm([[1.0, 2.0]]))
            await server.stop(None)
            return payload_from_proto(reply)

        out = run(go())
        np.testing.assert_allclose(out.array, [[2.0, 4.0]])

    def test_router_route_and_feedback(self):
        async def go():
            comp = PickSecond()
            server = grpc.aio.server()
            from seldon_core_tpu.runtime.grpc_service import ComponentGrpc, register

            register(server, ComponentGrpc(comp, name="r"))
            port = server.add_insecure_port("127.0.0.1:0")
            await server.start()
            async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                stub = Stub(ch, "Router")
                reply = await stub.Route(_sm([[1.0]]))
                fb = pb.Feedback()
                fb.reward = 0.7
                fb.response.meta.routing["r"] = 1
                await stub.SendFeedback(fb)
            await server.stop(None)
            return payload_from_proto(reply), comp.last

        out, last = run(go())
        assert int(np.asarray(out.array).ravel()[0]) == 1
        assert last == (pytest.approx(0.7), 1)

    def test_combiner_aggregate(self):
        class Averager:
            def aggregate(self, Xs, names):
                return np.mean(np.stack([np.asarray(x) for x in Xs]), axis=0)

        async def go():
            server = grpc.aio.server()
            from seldon_core_tpu.runtime.grpc_service import ComponentGrpc, register

            register(server, ComponentGrpc(Averager(), name="c"))
            port = server.add_insecure_port("127.0.0.1:0")
            await server.start()
            req = pb.SeldonMessageList()
            req.seldonMessages.append(_sm([[0.0, 2.0]]))
            req.seldonMessages.append(_sm([[2.0, 4.0]]))
            async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                reply = await Stub(ch, "Combiner").Aggregate(req)
            await server.stop(None)
            return payload_from_proto(reply)

        out = run(go())
        np.testing.assert_allclose(out.array, [[1.0, 3.0]])

    def test_error_maps_to_failure_status(self):
        class Broken:
            def predict(self, X, names):
                raise RuntimeError("nope")

        async def go():
            server = grpc.aio.server()
            from seldon_core_tpu.runtime.grpc_service import ComponentGrpc, register

            register(server, ComponentGrpc(Broken(), name="b"))
            port = server.add_insecure_port("127.0.0.1:0")
            await server.start()
            async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                reply = await Stub(ch, "Model").Predict(_sm([[1.0]]))
            await server.stop(None)
            return reply

        reply = run(go())
        assert reply.status.status == pb.Status.FAILURE


class TestEngineGrpc:
    def test_seldon_predict_default_graph(self):
        async def go():
            svc = PredictionService(
                PredictorSpec.model_validate(
                    {"name": "p", "graph": {"name": "m", "type": "MODEL", "implementation": "SIMPLE_MODEL"}}
                )
            )
            await svc.start()
            server = await start_engine_grpc(svc, 0)
            async with grpc.aio.insecure_channel(f"127.0.0.1:{server.bound_port}") as ch:
                reply = await Stub(ch, "Seldon").Predict(_sm([[5.0, 6.0, 7.0]]))
            await server.stop(None)
            await svc.close()
            return reply

        reply = run(go())
        assert reply.status.status == pb.Status.SUCCESS
        out = payload_from_proto(reply)
        np.testing.assert_allclose(out.array, [[0.1, 0.9, 0.5]])
        assert out.meta.puid  # engine assigned a request id


class TestEngineGrpcTransport:
    def test_engine_walks_remote_grpc_unit(self):
        """Graph node with endpoint type GRPC: engine -> microservice over
        a cached channel."""

        async def go():
            server = grpc.aio.server()
            from seldon_core_tpu.runtime.grpc_service import ComponentGrpc, register

            register(server, ComponentGrpc(Doubler(), name="d"))
            port = server.add_insecure_port("127.0.0.1:0")
            await server.start()

            svc = PredictionService(
                PredictorSpec.model_validate(
                    {
                        "name": "p",
                        "graph": {
                            "name": "d",
                            "type": "MODEL",
                            "endpoint": {
                                "service_host": "127.0.0.1",
                                "service_port": port,
                                "type": "GRPC",
                            },
                        },
                    }
                )
            )
            await svc.start()
            out = await svc.predict(Payload.from_array(np.array([[3.0, 4.0]])))
            await svc.close()  # also closes the engine's gRPC channel cache
            await server.stop(None)
            return out

        out = run(go())
        np.testing.assert_allclose(out.array, [[6.0, 8.0]])
        assert "d" in out.meta.request_path
