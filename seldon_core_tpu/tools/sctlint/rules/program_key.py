"""program-key: every graph param a jitted program factory reads must be
folded into ``_program_config``.

Compiled-program cache keys are ``(bucket/k/window,) + _program_config``.
A factory that closes over a config attribute NOT in that tuple bakes
the value into the traced program while the cache key says it doesn't
matter — two configurations silently share one compiled step, or a
mid-traffic value change recompiles under load.  This is the bug class
the runtime key-audit tests (tests/test_spec.py, test_chunked.py,
test_lora.py) catch one PR late; here it fails on the exact line.

Mechanics: in the method that assigns ``self._program_config = (...)``
the rule finds every program factory (a nested ``def`` passed to
``jax.jit`` or stored on a ``self.*_factory`` attribute, plus the
helpers those factories call), computes what each closes over, and
chases free variables back through single assignments to the
``self.<attr>`` they were derived from.  Each such attribute must
appear in the key tuple; ``os.environ`` reads inside a factory are
flagged unconditionally (fold the value through an attribute).
Deliberately-unkeyed values (they cannot affect the traced program)
are annotated ``# sct: program-key-ok <reason>`` where they are read.
"""

from __future__ import annotations

import ast
from typing import Iterable

from seldon_core_tpu.tools.sctlint.core import Context, Finding, Rule, dotted


def _key_attrs(assign: ast.Assign) -> set[str] | None:
    """Attribute names in ``self._program_config = (self.a, self.b, ...)``."""
    v = assign.value
    if not isinstance(v, ast.Tuple):
        return None
    out = set()
    for el in v.elts:
        if isinstance(el, ast.Attribute) and isinstance(el.value, ast.Name) \
                and el.value.id == "self":
            out.add(el.attr)
    return out


def _is_program_config_assign(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Assign)
        and len(node.targets) == 1
        and isinstance(node.targets[0], ast.Attribute)
        and node.targets[0].attr == "_program_config"
    )


def _factory_names(method: ast.FunctionDef) -> set[str]:
    """Nested defs that become compiled programs: passed to jax.jit or
    assigned to a ``self.*`` slot whose name mentions ``factory``."""
    nested = {
        n.name for n in ast.iter_child_nodes(method)
        if isinstance(n, ast.FunctionDef)
    }
    out: set[str] = set()
    for n in ast.walk(method):
        if isinstance(n, ast.Call) and dotted(n.func) in (
            "jax.jit", "jax.pjit", "pjit", "jit"
        ):
            for a in n.args:
                if isinstance(a, ast.Name) and a.id in nested:
                    out.add(a.id)
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            t = n.targets[0]
            if isinstance(t, ast.Attribute) and "factory" in t.attr:
                for sub in ast.walk(n.value):
                    if isinstance(sub, ast.Name) and sub.id in nested:
                        out.add(sub.id)
    return out


def _bound_names(fn: ast.FunctionDef) -> set[str]:
    bound = {a.arg for a in (
        fn.args.args + fn.args.posonlyargs + fn.args.kwonlyargs
    )}
    if fn.args.vararg:
        bound.add(fn.args.vararg.arg)
    if fn.args.kwarg:
        bound.add(fn.args.kwarg.arg)
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                for el in ast.walk(t):
                    if isinstance(el, ast.Name):
                        bound.add(el.id)
        elif isinstance(n, (ast.For, ast.comprehension)):
            tgt = n.target
            for el in ast.walk(tgt):
                if isinstance(el, ast.Name):
                    bound.add(el.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bound.add(n.name)
        elif isinstance(n, ast.withitem) and n.optional_vars is not None:
            for el in ast.walk(n.optional_vars):
                if isinstance(el, ast.Name):
                    bound.add(el.id)
    return bound


def check(ctx: Context) -> Iterable[Finding]:
    out: list[Finding] = []
    for src in ctx.py:
        if src.tree is None:
            continue
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for method in cls.body:
                if not isinstance(method, ast.FunctionDef):
                    continue
                key_assign = next(
                    (n for n in ast.walk(method)
                     if _is_program_config_assign(n)), None
                )
                if key_assign is None:
                    continue
                keys = _key_attrs(key_assign)
                if keys is None:
                    out.append(Finding(
                        "program-key", src.rel, key_assign.lineno,
                        "_program_config must be a literal tuple of "
                        "self.<attr> reads so the key audit can "
                        "cross-reference it",
                        src.snippet(key_assign.lineno),
                    ))
                    continue
                out.extend(_check_method(src, cls, method, keys))
    return out


def _check_method(src, cls, method, keys) -> Iterable[Finding]:
    nested = {
        n.name: n for n in ast.iter_child_nodes(method)
        if isinstance(n, ast.FunctionDef)
    }
    factories = _factory_names(method)
    if not factories:
        return []
    # factories plus the nested helpers they call, transitively
    todo, scope = list(factories), set()
    while todo:
        name = todo.pop()
        if name in scope or name not in nested:
            continue
        scope.add(name)
        for n in ast.walk(nested[name]):
            if isinstance(n, ast.Name) and n.id in nested:
                todo.append(n.id)

    # one assignment map for the enclosing method body (top level only)
    assigns: dict[str, ast.Assign] = {}
    for n in ast.iter_child_nodes(method):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    assigns[t.id] = n

    out: list[Finding] = []
    flagged: set[tuple[int, str]] = set()

    def flag(line: int, attr: str, via: str) -> None:
        if (line, attr) in flagged:
            return
        flagged.add((line, attr))
        out.append(Finding(
            "program-key", src.rel, line,
            f"program factory reads self.{attr}{via} but "
            f"'{attr}' is not folded into _program_config — two "
            "configs differing only in it would share a compiled "
            "program (or annotate why it cannot affect the trace)",
            src.snippet(line),
        ))

    for fname in scope:
        fn = nested[fname]
        bound = _bound_names(fn)
        for n in ast.walk(fn):
            # direct self.<attr> read inside a factory
            if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name) \
                    and n.value.id == "self" and isinstance(n.ctx, ast.Load):
                if n.attr not in keys and n.attr != "_program_config":
                    flag(n.lineno, n.attr, "")
            # env read at trace time
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d.startswith(("os.environ", "os.getenv")):
                    out.append(Finding(
                        "program-key", src.rel, n.lineno,
                        "program factory reads the environment at trace "
                        "time — fold the value through a keyed "
                        "self.<attr> instead",
                        src.snippet(n.lineno),
                    ))
            # free variable derived from an unkeyed self.<attr>
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                    and n.id not in bound and n.id in assigns \
                    and n.id not in scope:
                rhs = assigns[n.id]
                for sub in ast.walk(rhs.value):
                    if isinstance(sub, ast.Attribute) \
                            and isinstance(sub.value, ast.Name) \
                            and sub.value.id == "self" \
                            and sub.attr not in keys:
                        flag(rhs.lineno, sub.attr,
                             f" (via local '{n.id}')")
    return out


RULE = Rule(
    id="program-key",
    summary="jitted factories only read params folded into _program_config",
    explain=__doc__,
    check=check,
)
