"""sct-wrap (testing/wrap.py): the assemble-and-verify wrapper path for
any-language models — the reference's s2i story as one gated command."""

import os
import shutil

import pytest

from seldon_core_tpu.testing import wrap

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestAssemble:
    def test_python_context(self, tmp_path):
        ctx = wrap.assemble(
            os.path.join(REPO_ROOT, "examples", "iris"),
            "IrisClassifier",
            out=str(tmp_path / "ctx"),
        )
        df = open(os.path.join(ctx, "Dockerfile")).read()
        assert "MODEL_NAME=IrisClassifier" in df
        assert os.path.exists(os.path.join(ctx, "IrisClassifier.py"))
        assert os.path.exists(os.path.join(ctx, "contract.json"))

    def test_missing_required_file_fails_loudly(self, tmp_path):
        (tmp_path / "empty").mkdir()
        with pytest.raises(SystemExit, match="WRAPPING.md"):
            wrap.assemble(str(tmp_path / "empty"), "Nope")

    def test_r_context_carries_runtime(self, tmp_path):
        model = tmp_path / "rmodel"
        model.mkdir()
        (model / "model.R").write_text(
            "predict_model <- function(X) X * 2\n"
        )
        ctx = wrap.assemble(str(model), "rr", language="r",
                            out=str(tmp_path / "rctx"))
        assert os.path.exists(os.path.join(ctx, "microservice.R"))
        assert "rocker/r-base" in open(os.path.join(ctx, "Dockerfile")).read()

    def test_generic_context(self, tmp_path):
        model = tmp_path / "srv"
        model.mkdir()
        (model / "run.sh").write_text("exec my-server\n")
        ctx = wrap.assemble(str(model), "yr", language="generic",
                            out=str(tmp_path / "gctx"))
        assert 'ENTRYPOINT ["sh", "run.sh"]' in open(
            os.path.join(ctx, "Dockerfile")
        ).read()


class TestLiveGate:
    """--test: launch from the context exactly as the image would and
    contract-drive it (the s2i assemble+verify analogue, CI-exercised)."""

    def test_python_iris_gate_passes(self, tmp_path):
        ctx = wrap.assemble(
            os.path.join(REPO_ROOT, "examples", "iris"),
            "IrisClassifier",
            out=str(tmp_path / "ctx"),
        )
        summary = wrap.test_context(ctx, "IrisClassifier", "python", port=19791)
        assert summary["ok"], summary

    @pytest.mark.slow
    def test_cpp_gate_passes(self, tmp_path):
        if shutil.which("g++") is None:
            pytest.skip("no g++")
        ctx = wrap.assemble(
            os.path.join(REPO_ROOT, "examples", "cpp-model"),
            "iris-native",
            language="cpp",
            out=str(tmp_path / "cppctx"),
        )
        summary = wrap.test_context(ctx, "iris-native", "cpp", port=19792)
        assert summary["ok"], summary

    def test_gate_without_contract_fails_with_instructions(self, tmp_path):
        model = tmp_path / "m"
        model.mkdir()
        (model / "Thing.py").write_text(
            "class Thing:\n    def predict(self, X, names):\n        return X\n"
        )
        ctx = wrap.assemble(str(model), "Thing", out=str(tmp_path / "c"))
        with pytest.raises(SystemExit, match="contract.json"):
            wrap.test_context(ctx, "Thing", "python", port=19793)


def test_r_runtime_copies_stay_in_sync():
    """The packaged R runtime (shipped as package data) and the browsable
    wrappers/r/microservice.R must be the same file."""
    packaged = os.path.join(
        REPO_ROOT, "seldon_core_tpu", "testing", "data", "microservice.R"
    )
    browsable = os.path.join(REPO_ROOT, "wrappers", "r", "microservice.R")
    assert open(packaged).read() == open(browsable).read()
