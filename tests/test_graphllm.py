"""LLM-native inference graph tests (docs/GRAPHS.md).

Covers the graph plane this PR adds — cascade routing, the embeddings
endpoint, the semantic cache tier, and guardrail nodes — plus its
acceptance gates: escalation and non-escalation paths each produce a
stitched trace (``cascade.route`` span with tier + confidence) and
BIT-IDENTICAL tokens to calling the chosen tier directly; a semantic
paraphrase hit spends ZERO generation device steps; the confidence
signal adds ZERO host syncs per request; pooled embedding vectors are
pinned-stable, tp=2 mesh included; a CR spec roll flushes the exact AND
semantic namespaces together; and the determinism contract audits both
ways (a cascade never engages the whole-graph response cache, a
classifier-free guardrail never disengages it).
"""

import asyncio
import base64
import json

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from seldon_core_tpu.cache import ResponseCache, SemanticCache
from seldon_core_tpu.contract import DataKind, Payload
from seldon_core_tpu.engine.app import EngineApp
from seldon_core_tpu.engine.service import PredictionService
from seldon_core_tpu.gateway.app import GatewayApp
from seldon_core_tpu.gateway.store import DeploymentRecord, DeploymentStore
from seldon_core_tpu.graph.spec import PredictorSpec
from seldon_core_tpu.graph.units import GraphUnitError
from seldon_core_tpu.graphllm import CascadeRouter, Guardrail

run = asyncio.run


# ---------------------------------------------------------------------------
# spec helpers
# ---------------------------------------------------------------------------


def _tier(name: str, n_layers: int) -> dict:
    """One generative cascade tier: tiny llama, layer count = the tier's
    'size' (same preset + rng -> per-shape deterministic weights, so a
    solo build of the same spec answers bit-identically)."""
    return {
        "name": name, "type": "MODEL", "implementation": "JAX_GENERATIVE",
        "parameters": [
            {"name": "family", "value": "llama", "type": "STRING"},
            {"name": "preset", "value": "tiny", "type": "STRING"},
            {"name": "n_layers", "value": str(n_layers), "type": "INT"},
            {"name": "n_slots", "value": "2", "type": "INT"},
            {"name": "max_new_tokens", "value": "4", "type": "INT"},
            {"name": "conf_signal", "value": "true", "type": "BOOL"},
        ],
    }


def _cascade_spec() -> dict:
    return {
        "name": "llmcasc",
        "graph": {
            "name": "casc", "type": "CASCADE_ROUTER",
            "implementation": "CASCADE_ROUTER",
            "parameters": [
                {"name": "threshold", "value": "2.0", "type": "FLOAT"},
            ],
            "children": [_tier("small", 2), _tier("large", 4)],
        },
    }


EMBED_SPEC = {
    "name": "emb",
    "graph": {
        "name": "gen", "type": "MODEL", "implementation": "JAX_GENERATIVE",
        "parameters": [
            {"name": "family", "value": "llama", "type": "STRING"},
            {"name": "preset", "value": "tiny", "type": "STRING"},
            {"name": "n_slots", "value": "2", "type": "INT"},
            {"name": "max_new_tokens", "value": "4", "type": "INT"},
            {"name": "embed", "value": "true", "type": "BOOL"},
        ],
    },
}

SIMPLE = {
    "name": "p",
    "graph": {"name": "m", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
}

PROMPT = [5, 9, 2, 17]
GEN_BODY = {"strData": json.dumps({"tokens": PROMPT, "max_new_tokens": 4})}


async def _engine_client(spec, *, service=None) -> tuple[TestClient, PredictionService]:
    if service is None:
        service = PredictionService(PredictorSpec.model_validate(spec))
    app = EngineApp(service).build()
    client = TestClient(TestServer(app))
    await client.start_server()
    return client, service


async def _gateway_client(engine_port: int) -> tuple[TestClient, GatewayApp, str]:
    store = DeploymentStore()
    store.put(DeploymentRecord(
        name="dep", oauth_key="key1", oauth_secret="sec1",
        engine_host="127.0.0.1", engine_rest_port=engine_port,
    ))
    gw = GatewayApp(store)
    client = TestClient(TestServer(gw.build()))
    await client.start_server()
    resp = await client.post(
        "/oauth/token", data={"client_id": "key1", "client_secret": "sec1"}
    )
    token = (await resp.json())["access_token"]
    return client, gw, token


def _tokens(body: dict) -> list:
    return json.loads(body["strData"])["tokens"]


# ---------------------------------------------------------------------------
# unit: cascade decision policy
# ---------------------------------------------------------------------------


class TestCascadeRouterUnit:
    def test_confident_answer_ships(self):
        r = CascadeRouter(threshold=2.0)
        assert r.decide(3.5, 0, 2) == (False, "confident")
        assert r.last_confidence == 3.5

    def test_low_confidence_escalates(self):
        r = CascadeRouter(threshold=2.0)
        assert r.decide(0.4, 0, 2) == (True, "low-confidence")

    def test_no_signal_trusts_cheap_tier(self):
        # conf_signal off / non-generative tier: never escalate blind
        r = CascadeRouter(threshold=2.0)
        assert r.decide(None, 0, 2) == (False, "no-signal")

    def test_deadline_budget_blocks_escalation(self):
        from seldon_core_tpu import qos

        r = CascadeRouter(threshold=2.0, ttft_ms=50.0)
        qos.set_budget_ms(20.0)  # 20ms left < 50ms expected TTFT
        try:
            assert r.decide(0.1, 0, 2) == (False, "deadline-budget")
        finally:
            qos.set_budget_ms(None)

    def test_read_confidence_forms(self):
        r = CascadeRouter()

        def p(data):
            return Payload(data, [], DataKind.STRING)

        assert r.read_confidence(p(json.dumps({"confidence": 1.5}))) == 1.5
        # batch replies carry a list; the mean drives the decision
        assert r.read_confidence(p(json.dumps({"confidence": [1.0, 3.0]}))) == 2.0
        assert r.read_confidence(p(json.dumps({"tokens": [1]}))) is None
        assert r.read_confidence(p("not json")) is None
        assert r.read_confidence(Payload(np.zeros(2), [], DataKind.NDARRAY)) is None

    def test_ledger_and_metrics_surface(self):
        r = CascadeRouter(name="c")
        r.note_served(0)
        r.note_served(1)
        r.note_escalation()
        r.decide(1.25, 0, 2)
        keys = {m["key"]: m["value"] for m in r.metrics()}
        assert keys["c_cascade_escalations"] == 1
        assert keys["c_cascade_served_tier0"] == 1
        assert keys["c_cascade_served_tier1"] == 1
        assert r.tags() == {"cascade_confidence": 1.25}


# ---------------------------------------------------------------------------
# unit: guardrail policy pipeline
# ---------------------------------------------------------------------------


class TestGuardrailUnit:
    def test_block_regex_rejects(self):
        g = Guardrail(block="forbidden,secret")
        with pytest.raises(GraphUnitError, match="blocked"):
            g.apply("this is ForBidden text")  # IGNORECASE
        assert g.actions["block"] == 1

    def test_pii_scrub_all_patterns(self):
        g = Guardrail()
        clean, actions = g.apply(
            "mail a.user+x@example.com ssn 123-45-6789 phone (415) 555-1234"
        )
        assert "example.com" not in clean
        assert "123-45-6789" not in clean
        assert "555-1234" not in clean
        assert clean.count("[REDACTED]") == 3
        assert actions == ["scrub"]

    def test_stop_tokens_and_truncate(self):
        g = Guardrail(scrub_pii="0", stop_tokens="END", max_chars=4)
        clean, actions = g.apply("abcdefEND tail")
        # stop cut first ("abcdef"), then the length policy to 4 chars
        assert clean == "abcd"
        assert actions == ["stop", "truncate"]

    def test_classifier_hook_verdicts(self):
        allow = Guardrail(classifier=lambda t: True)
        assert allow.apply("ok")[0] == "ok"
        deny = Guardrail(classifier=lambda t: (False, "policy"))
        with pytest.raises(GraphUnitError, match="policy"):
            deny.apply("ok")

    def test_clean_text_passes_untouched(self):
        g = Guardrail()
        clean, actions = g.apply("hello world")
        assert clean == "hello world" and actions == []
        assert g.actions["pass"] == 1

    def test_non_string_payload_passes_through(self):
        g = Guardrail()
        p = Payload(np.array([[1, 2]]), [], DataKind.NDARRAY)
        assert g.transform_input_raw(p) is p

    def test_pre_guardrail_reseeds_qos_class(self):
        from seldon_core_tpu import qos

        g = Guardrail(qos_class="batch")
        qos.set_priority("interactive")
        out = g.transform_input_raw(Payload("hi", [], DataKind.STRING))
        try:
            # downstream of a PRE-guardrail runs under ITS class
            assert qos.get_priority() == "batch"
            assert json_safe(out.data) == "hi"
        finally:
            qos.set_priority("interactive")

    def test_determinism_contract(self):
        # pure regex/length policies keep the caching plane engaged ...
        assert Guardrail().DETERMINISTIC is True
        # ... a (possibly stateful) classifier hook disengages it
        assert Guardrail(classifier=lambda t: True).DETERMINISTIC is False


def json_safe(v):
    return v if isinstance(v, str) else v.decode("utf-8")


# ---------------------------------------------------------------------------
# unit: semantic cache tier
# ---------------------------------------------------------------------------


class TestSemanticCacheUnit:
    V = np.array([1.0, 0.0, 0.0], np.float32)

    def test_similarity_threshold(self):
        c = SemanticCache(sim_threshold=0.9)
        c.put("ns", self.V, b"answer", "tag")
        near = np.array([0.99, 0.05, 0.0], np.float32)  # cos ~0.9987
        far = np.array([0.5, 0.86, 0.0], np.float32)  # cos ~0.5
        assert c.lookup("ns", near, "tag") == b"answer"
        assert c.lookup("ns", far, "tag") is None
        assert (c.hits, c.misses) == (1, 1)
        assert c.last_sim is None  # miss resets the gauge

    def test_namespace_and_tag_isolation(self):
        c = SemanticCache(sim_threshold=0.9)
        c.put("a", self.V, b"va", "t1")
        # other namespace: invisible
        assert c.lookup("b", self.V, "t1") is None
        # same namespace, rolled spec-hash: unhittable by construction
        assert c.lookup("a", self.V, "t2") is None
        assert c.lookup("a", self.V, "t1") == b"va"

    def test_ttl_expiry(self):
        c = SemanticCache(ttl_s=0.0)
        c.put("ns", self.V, b"v", "t")
        assert c.lookup("ns", self.V, "t") is None
        assert c.expirations == 1

    def test_entry_and_byte_bounds_evict_oldest(self):
        c = SemanticCache(max_entries=2, max_bytes=10_000)
        for i in range(4):
            vec = np.zeros(3, np.float32)
            vec[i % 3] = 1.0
            c.put("ns", vec, bytes([i]), "t")
        assert len(c._entries) == 2
        assert c.evictions == 2
        big = SemanticCache(max_bytes=64)
        big.put("ns", self.V, b"x" * 1000, "t")  # oversized: uncacheable
        assert len(big._entries) == 0

    def test_flush_counts_per_namespace(self):
        c = SemanticCache()
        c.put("a", self.V, b"1", "t")
        c.put("b", self.V, b"2", "t")
        assert c.flush("a") == 1
        assert c.flush("a") == 0  # empty flush doesn't count
        assert c.flush() == 1  # clear-all drops the rest
        snap = c.snapshot()
        assert snap["flushes"] == 2
        assert snap["flushes_by_namespace"] == {"a": 1, "b": 1}
        assert snap["entries"] == 0 and snap["bytes"] == 0

    def test_snapshot_shape(self):
        c = SemanticCache(sim_threshold=0.8)
        c.put("ns", self.V, b"v", "t")
        c.lookup("ns", self.V, "t")
        snap = c.snapshot()
        assert snap["tier"] == "semantic"
        assert snap["hits"] == 1 and snap["hit_rate"] == 1.0
        assert snap["last_similarity"] == 1.0
        assert snap["sim_threshold"] == 0.8


class TestResponseCacheNamespaceFlush:
    def test_exact_tier_counts_flushes_per_namespace(self):
        """Small-fix satellite: /stats/cache attributes flushes to the
        deployment namespace that rolled, not just a global count."""
        c = ResponseCache("t")
        c.put("a", "k", b"1")
        c.put("b", "k", b"2")
        c.flush("a")
        c.flush(None)
        snap = c.snapshot()
        assert snap["flushes_by_namespace"] == {"a": 1, "b": 1}


# ---------------------------------------------------------------------------
# e2e: cascade through gateway -> walker -> both tiers
# ---------------------------------------------------------------------------


class TestCascadeE2E:
    """The pinned graph-spec acceptance flow: one two-tier cascade engine
    behind the gateway; forcing the threshold to the extremes drives BOTH
    paths, each bit-identical to the chosen tier built solo."""

    def _solo_tokens(self, n_layers: int) -> list:
        """Build the tier's spec standalone and call it directly — the
        bit-identity baseline for the cascade's answer."""
        from seldon_core_tpu.models.registry import build_generative_component

        async def go():
            comp = build_generative_component(
                "llama", preset="tiny", n_layers=n_layers, n_slots=2,
                max_new_tokens=4, conf_signal=True,
            )
            try:
                out = await comp.predict_raw(
                    Payload(GEN_BODY["strData"], [], DataKind.STRING)
                )
                return _tokens({"strData": out.data})
            finally:
                await comp.close()

        return run(go())

    def test_both_paths_pinned(self, monkeypatch):
        monkeypatch.setenv("ENGINE_WARMUP", "0")
        from seldon_core_tpu.obs import RECORDER

        async def go():
            service = PredictionService(
                PredictorSpec.model_validate(_cascade_spec())
            )
            # determinism audit: wiring the whole-graph tiers must NOT
            # engage — the cascade is non-deterministic by contract — but
            # the node tier still serves the deterministic tier children
            service.response_cache = ResponseCache("engine")
            service.semantic_cache = SemanticCache()
            service.node_cache = ResponseCache("node")
            engine, service = await _engine_client(None, service=service)
            gw, gwapp, token = await _gateway_client(engine.server.port)
            hdrs = {"Authorization": f"Bearer {token}"}
            router = next(
                comp for _n, comp in service.walker.iter_components()
                if isinstance(comp, CascadeRouter)
            )

            async def ask():
                r = await gw.post(
                    "/api/v0.1/predictions", json=GEN_BODY, headers=hdrs
                )
                assert r.status == 200, await r.text()
                return await r.json(), r.headers.get("x-sct-cache")

            router.threshold = -1e9  # any confidence clears: never escalate
            cheap, _ = await ask()
            cheap2, hdr2 = await ask()  # exact repeat -> node-tier hit
            router.threshold = 1e9  # nothing clears: always escalate
            escalated, _ = await ask()
            stats = (await (await engine.get("/stats/cache")).json())["cache"]
            tr = RECORDER.stats(100)["traces"]
            await gw.close()
            await gwapp.close()
            await engine.close()
            return cheap, cheap2, hdr2, escalated, stats, tr, router

        cheap, cheap2, hdr2, escalated, stats, traces, router = run(go())

        # non-escalation path: tier 0's answer, bit-identical to solo
        assert cheap["meta"]["routing"]["casc"] == 0
        assert _tokens(cheap) == self._solo_tokens(2)
        # escalation path: tier 1's answer, bit-identical to solo
        assert escalated["meta"]["routing"]["casc"] == 1
        assert _tokens(escalated) == self._solo_tokens(4)
        assert _tokens(escalated) != _tokens(cheap)
        # the on-device signal rode the reply on both paths
        for body in (cheap, escalated):
            conf = json.loads(body["strData"])["confidence"]
            assert isinstance(conf, float) and np.isfinite(conf)
        assert cheap["meta"]["tags"]["cascade_confidence"] == round(
            json.loads(cheap["strData"])["confidence"], 4
        )

        # determinism audit: the cascade never caches whole-graph (neither
        # exact nor semantic tier engaged even though both were wired) ...
        assert stats["graph_deterministic"] is False
        assert stats["response"]["hits"] == 0
        assert stats["semantic"]["hits"] + stats["semantic"]["misses"] == 0
        assert hdr2 is None
        assert _tokens(cheap2) == _tokens(cheap)
        # ... but the deterministic tier children still node-cache
        assert stats["node"]["hits"] >= 1
        # served/escalation ledger
        assert router.served_by_tier == {0: 2, 1: 1}
        assert router.escalations == 1

        # stitched trace: cascade.route spans with tier/confidence/reason,
        # in the SAME trace as the engine's root span
        routes = []
        for t in traces:
            names = {s["name"] for s in t["spans"]}
            for s in t["spans"]:
                if s["name"] == "cascade.route":
                    assert "engine.predict" in names, names
                    routes.append(s["attrs"])
        assert len(routes) >= 3
        assert any(
            a["escalate"] is True and a["reason"] == "low-confidence"
            for a in routes
        )
        assert any(
            a["escalate"] is False and a["reason"] == "confident"
            for a in routes
        )
        assert all(
            a["tier"] == 0 and isinstance(a["confidence"], float)
            for a in routes
        )

    def test_cascade_over_numeric_tiers_trusts_cheap(self, monkeypatch):
        """No confidence signal (non-generative tiers) -> no blind
        escalation: tier 0 answers, routing recorded."""
        monkeypatch.setenv("ENGINE_WARMUP", "0")
        spec = {
            "name": "p",
            "graph": {
                "name": "casc", "type": "CASCADE_ROUTER",
                "implementation": "CASCADE_ROUTER",
                "children": [
                    {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                    {"name": "b", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                ],
            },
        }

        async def go():
            engine, service = await _engine_client(spec)
            r = await engine.post(
                "/api/v0.1/predictions",
                json={"data": {"ndarray": [[1.0, 2.0]]}},
            )
            body = await r.json()
            det = service.graph_deterministic()
            await engine.close()
            return r.status, body, det

        status, body, det = run(go())
        assert status == 200
        assert body["meta"]["routing"]["casc"] == 0
        assert det is False  # CASCADE_ROUTER poisons whole-graph determinism


# ---------------------------------------------------------------------------
# e2e: guardrails in a walked graph
# ---------------------------------------------------------------------------


class _Echo:
    """Componentless MODEL node: the walker's identity fallback echoes the
    payload, so the guardrail's rewrite is the only transformation."""

    DETERMINISTIC = True


class TestGuardrailE2E:
    def test_pre_guardrail_scrubs_and_blocks_over_rest(self, monkeypatch):
        monkeypatch.setenv("ENGINE_WARMUP", "0")
        spec = {
            "name": "p",
            "graph": {
                "name": "guard", "type": "GUARDRAIL",
                "implementation": "GUARDRAIL",
                "parameters": [
                    {"name": "block", "value": "attack", "type": "STRING"},
                ],
                "children": [{"name": "echo", "type": "MODEL"}],
            },
        }

        async def go():
            service = PredictionService(
                PredictorSpec.model_validate(spec),
                components={"echo": _Echo()},
            )
            engine, service = await _engine_client(None, service=service)
            r1 = await engine.post(
                "/api/v0.1/predictions",
                json={"strData": "reach me at me@example.com please"},
            )
            b1 = await r1.json()
            r2 = await engine.post(
                "/api/v0.1/predictions", json={"strData": "an ATTACK text"}
            )
            det = service.graph_deterministic()
            await engine.close()
            return b1, r2.status, det

        b1, blocked_status, det = run(go())
        assert "[REDACTED]" in b1["strData"]
        assert "example.com" not in b1["strData"]
        assert blocked_status == 500  # GraphUnitError surface
        # classifier-free guardrail + identity model: caching stays viable
        assert det is True

    def test_classifier_component_clears_graph_determinism(self):
        spec = {
            "name": "p",
            "graph": {
                "name": "guard", "type": "GUARDRAIL",
                "children": [{"name": "echo", "type": "MODEL"}],
            },
        }

        async def go():
            service = PredictionService(
                PredictorSpec.model_validate(spec),
                components={
                    "guard": Guardrail(classifier=lambda t: True),
                    "echo": _Echo(),
                },
            )
            await service.start()
            det = service.graph_deterministic()
            await service.close()
            return det

        assert run(go()) is False


# ---------------------------------------------------------------------------
# e2e: embeddings endpoint + semantic cache tier
# ---------------------------------------------------------------------------


class TestEmbeddingsAndSemanticE2E:
    def test_embeddings_route_and_paraphrase_hits(self, monkeypatch):
        monkeypatch.setenv("ENGINE_WARMUP", "0")

        async def go():
            service = PredictionService(PredictorSpec.model_validate(EMBED_SPEC))
            service.semantic_cache = SemanticCache(sim_threshold=0.9)
            engine, service = await _engine_client(None, service=service)

            # -- embeddings endpoint: rawTensor, flat + batch, pinned ----
            r1 = await engine.post(
                "/api/v0.1/embeddings", json={"tokens": PROMPT}
            )
            b1 = await r1.json()
            r2 = await engine.post(
                "/api/v0.1/embeddings",
                json={"tokens": [PROMPT, [7, 8, 9]]},
            )
            b2 = await r2.json()
            r3 = await engine.post(
                "/api/v0.1/embeddings", json={"tokens": PROMPT}
            )
            b3 = await r3.json()
            bad = await engine.post("/api/v0.1/embeddings", json={"nope": 1})

            # -- semantic tier: exact repeat then paraphrase ------------
            model = service.generative_units()[0].model
            p1 = await engine.post("/api/v0.1/predictions", json=GEN_BODY)
            miss_hdr = p1.headers.get("x-sct-cache")
            pb1 = await p1.json()
            steps_before = model.steps
            p2 = await engine.post("/api/v0.1/predictions", json=GEN_BODY)
            exact_hdr = p2.headers.get("x-sct-cache")
            pb2 = await p2.json()
            para_body = {
                "strData": json.dumps(
                    {"tokens": [5, 9, 2, 18], "max_new_tokens": 4}
                )
            }
            p3 = await engine.post("/api/v0.1/predictions", json=para_body)
            para_hdr = p3.headers.get("x-sct-cache")
            pb3 = await p3.json()
            steps_after = model.steps
            embeds = model.embeds
            stats = (await (await engine.get("/stats/cache")).json())["cache"]
            await engine.close()
            return (
                (r1.status, b1), (r2.status, b2), b3, bad.status,
                miss_hdr, (exact_hdr, pb1, pb2), (para_hdr, pb3),
                steps_before, steps_after, embeds, stats,
            )

        (
            (s1, b1), (s2, b2), b3, bad_status,
            miss_hdr, (exact_hdr, pb1, pb2), (para_hdr, pb3),
            steps_before, steps_after, embeds, stats,
        ) = run(go())

        # embeddings: (B, E) float32 through the typed rawTensor codec
        assert (s1, s2) == (200, 200)
        rt = b1["rawTensor"]
        assert rt["shape"] == [1, 64] and rt["dtype"] == "float32"
        assert b2["rawTensor"]["shape"] == [2, 64]
        vec = np.frombuffer(
            base64.b64decode(rt["data"]), np.float32
        )
        assert np.isfinite(vec).all() and float(np.abs(vec).sum()) > 0
        # pinned-stable: byte-identical on repeat
        assert b3["rawTensor"]["data"] == rt["data"]
        assert bad_status == 400

        # semantic tier: miss, exact hit, paraphrase hit — zero GENERATION
        # device steps for the hits (the embed pass is the lookup's cost)
        assert miss_hdr is None
        assert exact_hdr == "semantic" and pb2 == pb1
        assert para_hdr == "semantic" and pb3 == pb1
        assert steps_after == steps_before, (steps_before, steps_after)
        assert embeds >= 3  # every prediction request embedded its prompt
        sem = stats["semantic"]
        assert sem["hits"] == 2 and sem["misses"] == 1
        assert sem["last_similarity"] is not None
        assert 0.9 <= sem["last_similarity"] < 1.0  # the paraphrase, not 1.0

    def test_embeddings_400_without_embed_unit(self):
        async def go():
            engine, _ = await _engine_client(SIMPLE)
            r = await engine.post(
                "/api/v0.1/embeddings", json={"tokens": [1, 2, 3]}
            )
            body = await r.json()
            await engine.close()
            return r.status, body

        status, body = run(go())
        assert status == 400
        assert "SCT_EMBED" in body["status"]["info"]

    def test_pooled_vectors_pinned_under_tp2_mesh(self):
        """Acceptance: the tp-sharded mesh neither destabilizes nor
        meaningfully moves the pooled vectors."""
        import jax

        from seldon_core_tpu.executor.generation import (
            GenerationScheduler,
            GenerativeModel,
        )
        from seldon_core_tpu.models import llama
        from seldon_core_tpu.parallel import best_mesh

        def build(mesh, name):
            cfg = llama.Config.tiny(max_seq=128)
            params = llama.init_params(jax.random.PRNGKey(0), cfg)
            return GenerativeModel(
                cfg, params, n_slots=2, kv_block_size=16, embed=True,
                mesh=mesh,
                param_axes=(
                    llama.param_logical_axes(params) if mesh is not None else None
                ),
                name=name,
            )

        async def vecs(model):
            s = GenerationScheduler(model)
            a = await s.submit_embed(np.asarray(PROMPT, np.int32))
            b = await s.submit_embed(np.asarray(PROMPT, np.int32))
            await s.close()
            return a, b

        base_a, base_b = run(vecs(build(None, "emb-host")))
        mesh = best_mesh(2, tp=2)
        tp_a, tp_b = run(vecs(build(mesh, "emb-tp2")))
        # pinned-stable within each layout ...
        assert np.array_equal(base_a, base_b)
        assert np.array_equal(tp_a, tp_b)
        # ... and the sharded layout agrees with the host layout
        assert base_a.shape == tp_a.shape == (64,)
        np.testing.assert_allclose(tp_a, base_a, atol=2e-5, rtol=1e-4)


# ---------------------------------------------------------------------------
# gateway: one spec roll flushes BOTH tiers
# ---------------------------------------------------------------------------


class TestGatewayBothTierFlush:
    def test_spec_roll_flushes_exact_and_semantic_namespaces(self):
        store = DeploymentStore()
        gw = GatewayApp(store)
        gw.cache = ResponseCache("gateway")
        gw.semcache = SemanticCache()
        rec = DeploymentRecord(name="dep", oauth_key="k", oauth_secret="s")
        store.put(rec)
        vec = np.array([1.0, 0.0], np.float32)
        gw.cache.put("k", "some-key", b"stale-exact")
        gw.semcache.put("k", vec, b"stale-para", "oldhash")
        # CR spec edit: annotations change -> spec_hash rolls -> listener
        store.put(DeploymentRecord(
            name="dep", oauth_key="k", oauth_secret="s",
            annotations={"img": "v2"},
        ))
        assert gw.cache.get("k", "some-key") is None
        assert gw.semcache.lookup("k", vec, "oldhash") is None
        snap = gw.cache_snapshot()
        assert snap["response"]["flushes_by_namespace"] == {"k": 1}
        assert snap["semantic"]["flushes_by_namespace"] == {"k": 1}

    def test_endpoint_only_churn_keeps_both_tiers(self):
        store = DeploymentStore()
        gw = GatewayApp(store)
        gw.cache = ResponseCache("gateway")
        gw.semcache = SemanticCache()
        # watch-stamped hash: the CR watch hashes the SPEC, so endpoint
        # moves keep it (a directly-built record would derive a hash over
        # its endpoint fields instead)
        rec = DeploymentRecord(name="dep", oauth_key="k", oauth_secret="s",
                               engine_rest_port=9000, spec_hash="h1")
        store.put(rec)
        vec = np.array([1.0, 0.0], np.float32)
        tag = rec.spec_hash
        gw.cache.put("k", "key", b"warm")
        gw.semcache.put("k", vec, b"warm", tag)
        # autoscale grow/shrink: endpoints move, the spec hash doesn't
        store.put(DeploymentRecord(name="dep", oauth_key="k", oauth_secret="s",
                                   engine_rest_port=9001, spec_hash="h1"))
        assert gw.cache.get("k", "key").value == b"warm"
        assert gw.semcache.lookup("k", vec, tag) == b"warm"


# ---------------------------------------------------------------------------
# audits: host-sync parity + fleet merge
# ---------------------------------------------------------------------------


class TestConfidenceSignalHostSyncParity:
    def test_conf_signal_adds_zero_host_syncs(self):
        """Acceptance: the confidence margins ride the SAME fused fetch as
        the tokens — per-request host-sync deltas are EQUAL with the
        signal on and off."""
        import jax

        from seldon_core_tpu.executor.generation import (
            GenerationScheduler,
            GenerativeModel,
        )
        from seldon_core_tpu.models import llama
        from seldon_core_tpu.obs import host_sync_snapshot

        def build(conf, name):
            cfg = llama.Config.tiny(max_seq=128)
            params = llama.init_params(jax.random.PRNGKey(0), cfg)
            return GenerativeModel(
                cfg, params, n_slots=2, kv_block_size=16,
                conf_signal=conf, name=name,
            )

        def syncs_per_request(model):
            prompt = np.asarray(PROMPT, np.int32)
            infos = []

            async def go():
                # overlap=False: the overlapped pipeline's trailing
                # carry-consume sync lands inside the measurement window
                # timing-dependently on a loaded box; the sequential loop
                # makes the per-request count deterministic, and the
                # parity claim is about the conf signal, not overlap
                s = GenerationScheduler(model, overlap=False)
                # warm the compile; the measured request is steady-state
                await s.submit(prompt, max_new_tokens=8, temperature=0.0)
                before = dict(host_sync_snapshot())
                info = {}
                toks = await s.submit(
                    prompt, max_new_tokens=8, temperature=0.0, info=info
                )
                after = dict(host_sync_snapshot())
                await s.close()
                infos.append(info)
                key = next(k for k in after if model.name in k)
                return after.get(key, 0) - before.get(key, 0), toks

            delta, toks = run(go())
            return delta, toks, infos[0]

        d_off, toks_off, info_off = syncs_per_request(build(False, "hsoff"))
        d_on, toks_on, info_on = syncs_per_request(build(True, "hson"))
        assert d_on == d_off, (d_off, d_on)
        # the signal arrived (and tokens are untouched by carrying it)
        assert "confidence" not in info_off
        assert np.isfinite(info_on["confidence"])
        # margins cover the decode steps; the prefill-sampled first token
        # carries none
        assert info_on["conf_tokens"] == 7
        assert np.array_equal(toks_on, toks_off)


class TestFleetSemcacheMerge:
    def test_semantic_section_merges_counter_exactly(self):
        """Two replicas' /stats/cache payloads: the fleet collector's
        numeric merge must sum the semantic tier like any other counter
        family — including the per-namespace flush map."""
        from seldon_core_tpu.obs.fleet import _merge_numeric

        def replica(hits, misses, flushes, ns_flushes):
            return {
                "cache": {
                    "graph_deterministic": True,  # bool: never summed
                    "semantic": {
                        "tier": "semantic",
                        "hits": hits, "misses": misses,
                        "flushes": flushes,
                        "flushes_by_namespace": ns_flushes,
                    },
                }
            }

        into: dict = {}
        _merge_numeric(into, replica(3, 1, 1, {"dep": 1}))
        _merge_numeric(into, replica(2, 4, 2, {"dep": 1, "other": 1}))
        sem = into["cache"]["semantic"]
        assert sem["hits"] == 5 and sem["misses"] == 5
        assert sem["flushes"] == 3
        assert sem["flushes_by_namespace"] == {"dep": 2, "other": 1}
        assert "graph_deterministic" not in into["cache"]


# ---------------------------------------------------------------------------
# program-key audit: graph-built learned-speculation units (ISSUE 20)
# ---------------------------------------------------------------------------


def _spec_gen(name: str, extra: list[dict]) -> dict:
    return {
        "name": name,
        "graph": {
            "name": "gen", "type": "MODEL",
            "implementation": "JAX_GENERATIVE",
            "parameters": [
                {"name": "family", "value": "llama", "type": "STRING"},
                {"name": "preset", "value": "tiny", "type": "STRING"},
                {"name": "n_slots", "value": "2", "type": "INT"},
                {"name": "decode_block", "value": "2", "type": "INT"},
                {"name": "max_new_tokens", "value": "4", "type": "INT"},
                {"name": "spec_draft", "value": "2", "type": "INT"},
                *extra,
            ],
        },
    }


class TestProgramKeyAudit:
    """Graph-built generative units: every knob that changes the fused
    program's BODY must be a `_program_config` member — a collision would
    run the wrong compiled scan for the deployment's spec'd proposer."""

    @staticmethod
    def _built(spec) -> object:
        async def go():
            service = PredictionService(PredictorSpec.model_validate(spec))
            await service.start()
            try:
                return service.generative_units()[0].model
            finally:
                await service.close()

        return run(go())

    def test_heads_unit_program_config_pinned(self, monkeypatch):
        monkeypatch.setenv("ENGINE_WARMUP", "0")
        model = self._built(_spec_gen(
            "heads",
            [
                {"name": "spec_method", "value": "heads", "type": "STRING"},
                {"name": "spec_heads", "value": "3", "type": "INT"},
            ],
        ))
        assert model._program_config == (
            0, 2, model.spec_ngram, model.spec_hist, "heads", 3, None,
            None, model.prefill_chunk, model.decode_kernel,
            model.lora_rank, model.lora_slots, model.conf_signal,
        )

    def test_draft_unit_program_config_pinned(self, monkeypatch):
        monkeypatch.setenv("ENGINE_WARMUP", "0")
        model = self._built(_spec_gen(
            "draft",
            [
                {"name": "spec_method", "value": "draft", "type": "STRING"},
                {
                    "name": "spec_draft_model", "value": "truncate:1",
                    "type": "STRING",
                },
            ],
        ))
        assert model._program_config == (
            0, 2, model.spec_ngram, model.spec_hist, "draft", 0,
            ("truncate", 1), None, model.prefill_chunk,
            model.decode_kernel, model.lora_rank, model.lora_slots,
            model.conf_signal,
        )

    def test_breakdown_surfaces_per_method_acceptance(self, monkeypatch):
        """Satellite: /stats/breakdown splits acceptance by proposer for
        every generative unit (one deployment runs one proposer, so the
        split is the method-keyed snapshot map)."""
        monkeypatch.setenv("ENGINE_WARMUP", "0")

        async def go():
            engine, service = await _engine_client(_spec_gen(
                "bd",
                [
                    {
                        "name": "spec_method", "value": "heads",
                        "type": "STRING",
                    },
                    {"name": "spec_heads", "value": "2", "type": "INT"},
                ],
            ))
            await engine.post("/api/v0.1/predictions", json=GEN_BODY)
            body = await (await engine.get("/stats/breakdown")).json()
            await engine.close()
            return body

        body = run(go())
        (gen,) = body["generation"].values()  # keyed by model name
        assert gen["spec_method"] == "heads"
        assert gen["spec_heads"] == 2
        by = gen["accepted_tokens_per_step_by_method"]
        assert set(by) <= {"heads"}
        if by:
            assert by["heads"] == gen["accepted_tokens_per_step"]

    def test_decode_block_one_with_spec_is_build_error(self, monkeypatch):
        """Rider regression at the GRAPH layer: the loud error surfaces
        through spec validation, naming both knobs."""
        monkeypatch.setenv("ENGINE_WARMUP", "0")
        spec = _spec_gen("bad", [])
        for p in spec["graph"]["parameters"]:
            if p["name"] == "decode_block":
                p["value"] = "1"
        with pytest.raises(GraphUnitError) as ei:
            self._built(spec)
        msg = str(ei.value)
        assert "decode_block" in msg and "spec_draft" in msg
