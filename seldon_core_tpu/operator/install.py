"""Install-manifest rendering: everything needed to run the control plane.

The reference ships its install as Helm charts + ksonnet prototypes
(reference: helm-charts/seldon-core/templates/cluster-manager-deployment.yaml
:1-60, seldon-core/seldon-core/core.libsonnet:1-60).  Here the manifests are
rendered from the same Python constants the operator itself uses (ports,
images, CRD schema) so the install can never drift from the code, and the
rendered YAML is committed under ``deploy/`` for plain ``kubectl apply``
(golden-file tests pin the two together).

    python -m seldon_core_tpu.operator.install --out deploy/

renders (the committed defaults); Helm-values-style parameterization
(VERDICT r5 #8) comes from flags — ``--namespace``, ``--operator-image /
--gateway-image / --tap-image``, and ``--gateway-rest-port /
--gateway-grpc-port / --tap-port`` thread through every manifest (RBAC
subjects, Deployments, Services, probes, env, the token-store URL), so an
operator can land the plane in their own namespace/registry/ports without
hand-editing rendered YAML:

renders:

- ``crd.yaml``        the seldondeployments CRD (also created on operator
                      boot, 409-tolerant — reference CRDCreator.java:29-51)
- ``operator.yaml``   namespace, RBAC, operator Deployment
- ``gateway.yaml``    gateway RBAC + Deployment + Service (REST + gRPC)
- ``tap-broker.yaml`` request/response tap broker + Service
- ``install.yaml``    all of the above concatenated
"""

from __future__ import annotations

import argparse
import os
from typing import Any

from seldon_core_tpu.operator.crd import CRD_GROUP
from seldon_core_tpu.operator.kube_http import crd_manifest
from seldon_core_tpu.operator.resources import ENGINE_GRPC_PORT, ENGINE_REST_PORT

from seldon_core_tpu import __version__ as VERSION

NAMESPACE = "seldon-system"
# images pin to the release version (stamped by sct-release), not :latest —
# a restarted pod must not silently pick up a new build
OPERATOR_IMAGE = f"seldon-core-tpu/operator:{VERSION}"
GATEWAY_IMAGE = f"seldon-core-tpu/gateway:{VERSION}"
TAP_IMAGE = f"seldon-core-tpu/tap-broker:{VERSION}"

GATEWAY_REST_PORT = 8080
GATEWAY_GRPC_PORT = 5000
TAP_PORT = 7780


def _meta(name: str, namespace: str | None = NAMESPACE, **labels: str) -> dict[str, Any]:
    meta: dict[str, Any] = {"name": name, "labels": {"app": "seldon-core-tpu", **labels}}
    if namespace:
        meta["namespace"] = namespace
    return meta


def namespace_manifest(namespace: str = NAMESPACE) -> dict[str, Any]:
    return {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": namespace}}


def operator_rbac(namespace: str = NAMESPACE) -> list[dict[str, Any]]:
    """The operator owns CRs cluster-wide plus the workloads it emits
    (Deployments, multi-host StatefulSets, Services, Pods for slice rolls)."""
    return [
        {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": _meta("seldon-operator", namespace=namespace),
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": _meta("seldon-operator", namespace=None),
            "rules": [
                {
                    "apiGroups": [CRD_GROUP],
                    "resources": ["seldondeployments", "seldondeployments/status"],
                    "verbs": ["get", "list", "watch", "create", "update", "patch"],
                },
                {
                    "apiGroups": ["apiextensions.k8s.io"],
                    "resources": ["customresourcedefinitions"],
                    "verbs": ["get", "create"],
                },
                {
                    "apiGroups": ["apps"],
                    "resources": ["deployments", "statefulsets"],
                    "verbs": ["get", "list", "watch", "create", "update", "delete"],
                },
                {
                    "apiGroups": [""],
                    # pods: whole-slice restarts of multi-host StatefulSets
                    # (operator/controller.py::_roll_statefulset)
                    "resources": ["services", "pods"],
                    "verbs": ["get", "list", "watch", "create", "update", "delete"],
                },
            ],
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": _meta("seldon-operator", namespace=None),
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": "seldon-operator",
            },
            "subjects": [
                {
                    "kind": "ServiceAccount",
                    "name": "seldon-operator",
                    "namespace": namespace,
                }
            ],
        },
    ]


def operator_deployment(
    image: str = OPERATOR_IMAGE,
    watch_namespace: str = "default",
    namespace: str = NAMESPACE,
) -> dict[str, Any]:
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": _meta("seldon-operator", namespace=namespace, component="operator"),
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app.kubernetes.io/name": "seldon-operator"}},
            "template": {
                "metadata": {"labels": {"app.kubernetes.io/name": "seldon-operator"}},
                "spec": {
                    "serviceAccountName": "seldon-operator",
                    "containers": [
                        {
                            "name": "operator",
                            "image": image,
                            "command": ["sct-operator"],
                            "env": [
                                {"name": "SELDON_NAMESPACE", "value": watch_namespace},
                            ],
                            "resources": {
                                "requests": {"cpu": "100m", "memory": "256Mi"}
                            },
                        }
                    ],
                },
            },
        },
    }


def gateway_rbac(namespace: str = NAMESPACE) -> list[dict[str, Any]]:
    """The gateway only reads CRs (to register routes + OAuth clients)."""
    return [
        {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": _meta("seldon-gateway", namespace=namespace),
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": _meta("seldon-gateway", namespace=None),
            "rules": [
                {
                    "apiGroups": [CRD_GROUP],
                    "resources": ["seldondeployments"],
                    "verbs": ["get", "list", "watch"],
                }
            ],
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": _meta("seldon-gateway", namespace=None),
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": "seldon-gateway",
            },
            "subjects": [
                {
                    "kind": "ServiceAccount",
                    "name": "seldon-gateway",
                    "namespace": namespace,
                }
            ],
        },
    ]


def token_redis_manifests(namespace: str = NAMESPACE) -> list[dict[str, Any]]:
    """Memory-only redis backing the gateway's shared token store, so N
    gateway replicas accept each other's OAuth tokens (the reference
    deploys redis for exactly this: redis-memonly/redis-memonly.json.in,
    api-frontend/.../AuthorizationServerConfiguration.java:64-67)."""
    return [
        {
            # defense in depth: only gateway pods may reach the store
            "apiVersion": "networking.k8s.io/v1",
            "kind": "NetworkPolicy",
            "metadata": _meta("seldon-token-redis", namespace=namespace, component="token-store"),
            "spec": {
                "podSelector": {
                    "matchLabels": {"app.kubernetes.io/name": "seldon-token-redis"}
                },
                "policyTypes": ["Ingress"],
                "ingress": [
                    {
                        "from": [
                            {
                                "podSelector": {
                                    "matchLabels": {
                                        "app.kubernetes.io/name": "seldon-gateway"
                                    }
                                }
                            }
                        ],
                        "ports": [{"port": 6379, "protocol": "TCP"}],
                    }
                ],
            },
        },
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": _meta("seldon-token-redis", namespace=namespace, component="token-store"),
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"app.kubernetes.io/name": "seldon-token-redis"}},
                "template": {
                    "metadata": {"labels": {"app.kubernetes.io/name": "seldon-token-redis"}},
                    "spec": {
                        "containers": [
                            {
                                "name": "redis",
                                "image": "redis:7-alpine",
                                "env": [_redis_password_env()],
                                # tokens are reissuable: no persistence, cap
                                # memory like the reference's memonly config
                                "args": ["--requirepass", "$(REDIS_PASSWORD)",
                                         "--save", "", "--appendonly", "no",
                                         "--maxmemory", "64mb",
                                         "--maxmemory-policy", "allkeys-lru"],
                                "ports": [{"containerPort": 6379, "name": "redis"}],
                                "resources": {
                                    "requests": {"cpu": "50m", "memory": "96Mi"}
                                },
                            }
                        ],
                    },
                },
            },
        },
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta("seldon-token-redis", namespace=namespace),
            "spec": {
                "type": "ClusterIP",
                "selector": {"app.kubernetes.io/name": "seldon-token-redis"},
                "ports": [{"port": 6379, "targetPort": 6379, "name": "redis"}],
            },
        },
    ]


def _redis_password_env() -> dict[str, Any]:
    # the Secret is NOT part of install.yaml: shipping a literal password
    # in a public manifest would make every install share it, and
    # re-applying the file would reset a rotated one.  Operators create it
    # once (deploy/README.md):
    #   kubectl -n seldon-system create secret generic \
    #     seldon-token-redis-auth --from-literal=password=$(openssl rand -hex 24)
    return {
        "name": "REDIS_PASSWORD",
        "valueFrom": {
            "secretKeyRef": {"name": "seldon-token-redis-auth", "key": "password"}
        },
    }


def gateway_manifests(
    image: str = GATEWAY_IMAGE,
    namespace: str = NAMESPACE,
    rest_port: int = GATEWAY_REST_PORT,
    grpc_port: int = GATEWAY_GRPC_PORT,
) -> list[dict[str, Any]]:
    return [
        *token_redis_manifests(namespace=namespace),
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": _meta("seldon-gateway", namespace=namespace, component="gateway"),
            "spec": {
                # 2 replicas by default — tokens ride the shared store, so
                # any replica authenticates any client
                "replicas": 2,
                "selector": {"matchLabels": {"app.kubernetes.io/name": "seldon-gateway"}},
                "template": {
                    "metadata": {
                        "labels": {"app.kubernetes.io/name": "seldon-gateway"},
                        "annotations": {
                            "prometheus.io/scrape": "true",
                            "prometheus.io/path": "/prometheus",
                            "prometheus.io/port": str(rest_port),
                        },
                    },
                    "spec": {
                        "serviceAccountName": "seldon-gateway",
                        "containers": [
                            {
                                "name": "gateway",
                                "image": image,
                                "command": ["sct-gateway"],
                                "args": ["--watch"],
                                "env": [
                                    {"name": "GATEWAY_PORT", "value": str(rest_port)},
                                    {"name": "GATEWAY_GRPC_PORT", "value": str(grpc_port)},
                                    _redis_password_env(),
                                    {
                                        "name": "GATEWAY_TOKEN_STORE",
                                        # k8s expands $(REDIS_PASSWORD) from
                                        # the env var defined above
                                        "value": "redis://:$(REDIS_PASSWORD)@"
                                                 f"seldon-token-redis.{namespace}:6379",
                                    },
                                ],
                                "ports": [
                                    {"containerPort": rest_port, "name": "rest"},
                                    {"containerPort": grpc_port, "name": "grpc"},
                                ],
                                "readinessProbe": {
                                    "httpGet": {"path": "/ready", "port": rest_port},
                                    "initialDelaySeconds": 5,
                                    "periodSeconds": 5,
                                },
                                "resources": {
                                    "requests": {"cpu": "200m", "memory": "256Mi"}
                                },
                            }
                        ],
                    },
                },
            },
        },
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta("seldon-gateway", namespace=namespace),
            "spec": {
                "type": "ClusterIP",
                "selector": {"app.kubernetes.io/name": "seldon-gateway"},
                "ports": [
                    {"port": rest_port, "targetPort": rest_port, "name": "rest"},
                    {"port": grpc_port, "targetPort": grpc_port, "name": "grpc"},
                ],
            },
        },
    ]


def tap_broker_manifests(
    image: str = TAP_IMAGE,
    namespace: str = NAMESPACE,
    port: int = TAP_PORT,
) -> list[dict[str, Any]]:
    """Self-contained request/response tap (replaces the reference's
    Kafka+ZooKeeper install, kafka/kafka.json)."""
    return [
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": _meta("seldon-tap-broker", namespace=namespace, component="tap"),
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"app.kubernetes.io/name": "seldon-tap-broker"}},
                "template": {
                    "metadata": {"labels": {"app.kubernetes.io/name": "seldon-tap-broker"}},
                    "spec": {
                        "containers": [
                            {
                                "name": "tap-broker",
                                "image": image,
                                "command": ["sct-tap-broker"],
                                "args": ["--dir", "/data", "--port", str(port)],
                                "ports": [{"containerPort": port, "name": "tap"}],
                                "volumeMounts": [{"name": "data", "mountPath": "/data"}],
                                "resources": {
                                    "requests": {"cpu": "100m", "memory": "128Mi"}
                                },
                            }
                        ],
                        "volumes": [{"name": "data", "emptyDir": {}}],
                    },
                },
            },
        },
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta("seldon-tap-broker", namespace=namespace),
            "spec": {
                "type": "ClusterIP",
                "selector": {"app.kubernetes.io/name": "seldon-tap-broker"},
                "ports": [{"port": port, "targetPort": port, "name": "tap"}],
            },
        },
    ]


def render_all(
    *,
    namespace: str = NAMESPACE,
    operator_image: str = OPERATOR_IMAGE,
    gateway_image: str = GATEWAY_IMAGE,
    tap_image: str = TAP_IMAGE,
    gateway_rest_port: int = GATEWAY_REST_PORT,
    gateway_grpc_port: int = GATEWAY_GRPC_PORT,
    tap_port: int = TAP_PORT,
    watch_namespace: str = "default",
) -> dict[str, list[dict[str, Any]]]:
    """filename (sans .yaml) -> manifest list.  Defaults render the
    committed ``deploy/`` files byte-identically (golden tests pin that);
    overrides are the Helm-values equivalent for images/namespace/ports."""
    files = {
        "crd": [crd_manifest()],
        "operator": [
            namespace_manifest(namespace),
            *operator_rbac(namespace),
            operator_deployment(operator_image, watch_namespace, namespace),
        ],
        "gateway": [
            *gateway_rbac(namespace),
            *gateway_manifests(
                gateway_image, namespace, gateway_rest_port, gateway_grpc_port
            ),
        ],
        "tap-broker": tap_broker_manifests(tap_image, namespace, tap_port),
    }
    files["install"] = [m for group in ("crd", "operator", "gateway", "tap-broker") for m in files[group]]
    return files


def to_yaml(manifests: list[dict[str, Any]]) -> str:
    import yaml

    header = (
        "# Rendered by `python -m seldon_core_tpu.operator.install` — do not\n"
        "# hand-edit; golden tests (tests/test_install.py) pin this file to\n"
        "# the renderer.\n"
    )
    return header + yaml.safe_dump_all(manifests, sort_keys=True, default_flow_style=False)


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description="render install manifests")
    parser.add_argument("--out", default="deploy")
    parser.add_argument("--namespace", default=NAMESPACE,
                        help="control-plane namespace (default seldon-system)")
    parser.add_argument("--operator-image", default=OPERATOR_IMAGE)
    parser.add_argument("--gateway-image", default=GATEWAY_IMAGE)
    parser.add_argument("--tap-image", default=TAP_IMAGE)
    parser.add_argument("--gateway-rest-port", type=int, default=GATEWAY_REST_PORT)
    parser.add_argument("--gateway-grpc-port", type=int, default=GATEWAY_GRPC_PORT)
    parser.add_argument("--tap-port", type=int, default=TAP_PORT)
    parser.add_argument("--watch-namespace", default="default",
                        help="namespace the operator watches for CRs")
    args = parser.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)
    rendered = render_all(
        namespace=args.namespace,
        operator_image=args.operator_image,
        gateway_image=args.gateway_image,
        tap_image=args.tap_image,
        gateway_rest_port=args.gateway_rest_port,
        gateway_grpc_port=args.gateway_grpc_port,
        tap_port=args.tap_port,
        watch_namespace=args.watch_namespace,
    )
    for name, manifests in rendered.items():
        path = os.path.join(args.out, f"{name}.yaml")
        with open(path, "w") as f:
            f.write(to_yaml(manifests))
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
