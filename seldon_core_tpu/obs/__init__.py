"""Observability: in-process span recorder + per-stage latency flight
recorder for the serving hot path (see docs/OBSERVABILITY.md).

``RECORDER`` is the process-wide default (like ``utils/metrics.DEFAULT``);
exporters configured via env attach on first use by the serving apps
(``configure_exporters_from_env``).
"""

from __future__ import annotations

from seldon_core_tpu.obs.spans import (  # noqa: F401
    RECORDER,
    STAGE_BATCH_ASSEMBLY,
    STAGE_DEVICE_DISPATCH,
    STAGE_DEVICE_STEP,
    STAGE_ENGINE_ROUTE,
    STAGE_GATEWAY_RELAY,
    STAGE_NODE,
    STAGE_QUEUE_WAIT,
    STAGE_STREAM_FLUSH,
    STAGE_TTFT,
    STAGES,
    Span,
    SpanRecorder,
    current_engine_role,
    current_span,
    set_engine_role,
    set_process_role,
)
from seldon_core_tpu.obs.timeline import (  # noqa: F401
    TIMELINE,
    Timeline,
    TimelineLedger,
)
from seldon_core_tpu.obs.wire import (  # noqa: F401
    WIRE,
    WIRE_ENGINE_GRPC,
    WIRE_ENGINE_NODE,
    WIRE_ENGINE_REST,
    WIRE_GATEWAY_GRPC,
    WIRE_GATEWAY_H1,
    WIRE_GATEWAY_REST,
    WIRE_STAGES,
    WireCounter,
    WireRecorder,
)
from seldon_core_tpu.obs.probes import (  # noqa: F401
    LOOP_LAG,
    host_sync_snapshot,
    record_host_sync,
)
from seldon_core_tpu.obs.history import (  # noqa: F401
    BUCKET_EDGES,
    History,
    hist_percentile_ms,
    merge_hist,
    new_hist,
)
from seldon_core_tpu.obs.slo import (  # noqa: F401
    SLO_ANNOTATION,
    SloEngine,
    SloError,
    SloObjective,
    parse_slo,
)
from seldon_core_tpu.obs.fleet import FleetCollector  # noqa: F401
from seldon_core_tpu.obs.metering import (  # noqa: F401
    METER,
    UsageMeter,
    get_meter,
)


def configure_exporters_from_env(recorder: SpanRecorder | None = None) -> list:
    """Attach env-selected exporters (idempotent: second call is a no-op
    unless the recorder has none yet) and bind the span-ring/export drop
    gauges into /prometheus.  Called at engine/gateway boot."""
    from seldon_core_tpu.obs.export import exporters_from_env
    from seldon_core_tpu.obs.probes import install_obs_gauges

    rec = recorder or RECORDER
    if not rec.exporters:
        rec.exporters = exporters_from_env()
    install_obs_gauges()
    return rec.exporters


def wire_stats_payload() -> dict:
    """The ``GET /stats/wire`` body, shared by the engine and both gateway
    REST front ends: per-edge byte/MB-s counters plus the always-on
    probes (event-loop lag, host syncs per model)."""
    return {
        "wire": WIRE.snapshot(),
        "loop_lag": LOOP_LAG.snapshot(),
        "host_syncs": host_sync_snapshot(),
    }
