"""Operator entry point.

    python -m seldon_core_tpu.operator.app [--kube-url http://127.0.0.1:8001]

In-cluster by default (service-account config); ``--kube-url`` points at a
`kubectl proxy` for development.  Creates the CRD on startup then runs the
watch/reconcile loops until signalled.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import signal

from seldon_core_tpu.operator.controller import Controller
from seldon_core_tpu.operator.kube_http import HttpKube
from seldon_core_tpu.operator.resources import ENGINE_IMAGE_DEFAULT
from seldon_core_tpu.operator.watcher import OperatorLoop
from seldon_core_tpu.runtime import settings as _settings

log = logging.getLogger(__name__)


async def _start_fleet(kube, namespace: str, controller=None):
    """Fleet telemetry inside the operator (docs/OBSERVABILITY.md): a
    gateway-style CR watcher feeds the replica registry, the collector
    polls every replica's stats, and a small aiohttp app serves the
    aggregates on SCT_FLEET_PORT.  All of it runs on the operator's loop
    but never inside reconcile — scrapes are independent tasks.  With
    SCT_SCALE on, the autoscale reconciler (docs/AUTOSCALING.md) closes
    the loop off the same collector and serves its decision ledger on
    GET /stats/autoscale."""
    from aiohttp import web

    from seldon_core_tpu.gateway.store import DeploymentStore
    from seldon_core_tpu.gateway.watch import GatewayWatcher
    from seldon_core_tpu.obs.fleet import FleetCollector, build_stats_app

    store = DeploymentStore()
    watcher = GatewayWatcher(kube, store, namespace=namespace)
    await watcher.start()
    collector = FleetCollector(store, service="operator")
    await collector.start()
    autoscaler = None
    if _settings.get_bool("SCT_SCALE"):
        from seldon_core_tpu.autoscale.reconciler import AutoscaleReconciler

        autoscaler = AutoscaleReconciler(
            kube, store, collector,
            namespace=namespace, controller=controller,
        )
        await autoscaler.start()
    runner = web.AppRunner(build_stats_app(collector, autoscaler=autoscaler))
    await runner.setup()
    port = _settings.get_int("SCT_FLEET_PORT")
    site = web.TCPSite(runner, "0.0.0.0", port)
    await site.start()
    log.info("fleet collector serving /stats/fleet on :%d%s", port,
             " (autoscaler on)" if autoscaler is not None else "")

    async def stop() -> None:
        if autoscaler is not None:
            await autoscaler.stop()
        await collector.stop()
        await watcher.stop()
        await runner.cleanup()

    return stop


async def run(kube_url: str | None, namespace: str, engine_image: str) -> None:
    kube = HttpKube(kube_url)
    await kube.ensure_crd()
    controller = Controller(kube, engine_image=engine_image)
    loop = OperatorLoop(kube, controller, namespace=namespace)
    await loop.start()
    fleet_stop = None
    if _settings.get_bool("SCT_FLEET"):
        fleet_stop = await _start_fleet(kube, namespace, controller=controller)
    stop = asyncio.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        asyncio.get_running_loop().add_signal_handler(sig, stop.set)
    log.info("operator running (namespace=%s)", namespace)
    await stop.wait()
    if fleet_stop is not None:
        await fleet_stop()
    await loop.stop()
    await kube.close()


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description="seldon-core-tpu operator")
    parser.add_argument("--kube-url", default=os.environ.get("KUBE_URL") or None)
    parser.add_argument("--namespace", default=os.environ.get("SELDON_NAMESPACE", "default"))
    parser.add_argument(
        "--engine-image", default=os.environ.get("ENGINE_CONTAINER_IMAGE", ENGINE_IMAGE_DEFAULT)
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    asyncio.run(run(args.kube_url, args.namespace, args.engine_image))


if __name__ == "__main__":
    main()
