"""Elastic pool autoscaler (autoscale/policy.py, autoscale/reconciler.py).

Unit layers: the ``seldon.io/autoscale`` annotation grammar + admission
validation, the per-pool policy state machine on synthetic time
(hysteresis, dwell, slope lookahead, freshness decay), and signal
extraction off the fleet collector's merged aggregates.  Integration
layers: the reconciler actuating against a FakeKube (pool-mode endpoint
growth, drain-based shrink, aborted shrink on a failed drain), the
idempotent ``POST /admin/drain`` race semantics over a real generative
engine, and the kubesim diurnal e2e — load triples and ebbs, one
unified pool goes 1 -> N -> 1 with zero dropped streams, and role-typed
prefill/decode pools move INDEPENDENTLY (a TTFT surge scales only
prefill, an ITL surge only decode)."""

import asyncio
import json

import pytest
from aiohttp import web

from seldon_core_tpu.autoscale.policy import (
    AUTOSCALE_ANNOTATION,
    ROLE_SIGNALS,
    SIGNAL_KEYS,
    AutoscaleError,
    PoolPolicy,
    extract_signals,
    extract_slopes,
    parse_autoscale,
    pool_role,
)
from seldon_core_tpu.autoscale.reconciler import (
    ENDPOINTS_ANNOTATION,
    POOL_ANNOTATION,
    AutoscaleReconciler,
)
from seldon_core_tpu.gateway.store import (
    DeploymentRecord,
    DeploymentStore,
    Endpoint,
    EndpointDiff,
)
from seldon_core_tpu.obs.history import History, bin_samples
from seldon_core_tpu.operator.kube import FakeKube

run = asyncio.run


# ---------------------------------------------------------------------------
# annotation grammar
# ---------------------------------------------------------------------------


class TestGrammar:
    def test_full_spec_round_trips(self):
        spec = parse_autoscale(
            "min=2,max=6,ttft_p99_ms=250,itl_p99_ms=40,occupancy=0.85"
        )
        assert spec.min_replicas == 2 and spec.max_replicas == 6
        assert spec.target_map == {
            "ttft_p99_ms": 250.0, "itl_p99_ms": 40.0, "occupancy": 0.85,
        }
        # spec_str is canonical: re-parsing it is a fixed point
        assert parse_autoscale(spec.spec_str()) == spec

    def test_defaults_and_whitespace(self):
        spec = parse_autoscale(" queue_wait_ms = 500 , ")
        assert spec.min_replicas == 1 and spec.max_replicas == 8
        assert spec.target_map == {"queue_wait_ms": 500.0}

    @pytest.mark.parametrize("bad", [
        "min=1,max=8",                      # no signal targets
        "",                                  # empty
        "min=0,max=8,occupancy=0.8",        # min=0: drain needs a peer
        "min=4,max=2,occupancy=0.8",        # max < min
        "min=1,max=1000,occupancy=0.8",     # above the sanity cap
        "occupancy=0.8,occupancy=0.9",      # duplicate key
        "min=1,min=2,occupancy=0.8",        # duplicate bound
        "occupancy=1.5",                     # ratio out of (0, 1]
        "shed_rate=0",                       # ratio out of (0, 1]
        "ttft_p99_ms=0",                     # ms must be > 0
        "ttft_p99_ms=-5",                    # ms must be > 0
        "warp_factor=9",                     # unknown key
        "occupancy",                         # not key=value
        "min=fast,occupancy=0.8",           # non-integer bound
        "occupancy=hot",                     # non-numeric target
    ])
    def test_malformed_specs_rejected(self, bad):
        with pytest.raises(AutoscaleError):
            parse_autoscale(bad)

    def test_role_signal_families_cover_every_key(self):
        assert set(ROLE_SIGNALS["unified"]) == set(SIGNAL_KEYS)
        assert set(ROLE_SIGNALS["prefill"]) | set(ROLE_SIGNALS["decode"]) \
            == set(SIGNAL_KEYS)

    def test_pool_role_parsing(self):
        assert pool_role(None) == "unified"
        assert pool_role({}) == "unified"
        assert pool_role({"seldon.io/engine-role": " Prefill "}) == "prefill"
        assert pool_role({"seldon.io/engine-role": "decode"}) == "decode"
        assert pool_role({"seldon.io/engine-role": "warp"}) == "unified"

    def test_role_with_no_declared_target_rejected(self):
        # a decode pool whose spec only declares prefill signals would
        # never move — that's a config error, not a silent hold
        spec = parse_autoscale("min=1,max=4,ttft_p99_ms=250")
        with pytest.raises(AutoscaleError):
            PoolPolicy(spec, "decode")


class TestAdmission:
    def _cr(self, annotation=None):
        from seldon_core_tpu.operator.crd import SeldonDeployment

        meta = {"name": "mydep", "namespace": "default"}
        if annotation is not None:
            meta["annotations"] = {AUTOSCALE_ANNOTATION: annotation}
        return SeldonDeployment.from_dict({
            "metadata": meta,
            "spec": {
                "name": "mydep", "oauth_key": "k", "oauth_secret": "s",
                "predictors": [{
                    "name": "p1",
                    "graph": {"name": "m", "type": "MODEL",
                              "implementation": "SIMPLE_MODEL"},
                }],
            },
        })

    def test_valid_annotation_admitted(self):
        from seldon_core_tpu.operator.defaulting import defaulting, validate

        validate(defaulting(self._cr("min=1,max=4,occupancy=0.8")))

    def test_malformed_annotation_rejected_by_name(self):
        from seldon_core_tpu.operator.defaulting import (
            ValidationError, defaulting, validate,
        )

        with pytest.raises(ValidationError) as exc:
            validate(defaulting(self._cr("min=0,occupancy=2")))
        assert AUTOSCALE_ANNOTATION in str(exc.value)

    def test_absent_annotation_is_fine(self):
        from seldon_core_tpu.operator.defaulting import defaulting, validate

        validate(defaulting(self._cr(None)))


# ---------------------------------------------------------------------------
# policy state machine on synthetic time
# ---------------------------------------------------------------------------


def _policy(spec="min=1,max=8,queue_wait_ms=100", role="unified", **kw):
    defaults = dict(
        ewma_alpha=1.0, up_at=1.0, down_at=0.5, up_hold_s=60.0,
        down_hold_s=120.0, lookahead_s=60.0, max_step=2, stale_s=90.0,
    )
    defaults.update(kw)
    return PoolPolicy(parse_autoscale(spec), role, **defaults)


class TestPolicyStateMachine:
    def test_oscillation_inside_the_band_holds(self):
        p = _policy()
        # pressure bouncing between down_at and up_at: never moves
        for i, qw in enumerate([60.0, 95.0, 55.0, 99.0, 70.0]):
            now = float(i * 15)
            p.observe({"queue_wait_ms": qw}, now)
            d = p.decide(4, now)
            assert (d.direction, d.reason) == ("hold", "in-band"), (qw, d)

    def test_pressure_crossing_scales_up_with_proportional_step(self):
        p = _policy()
        p.observe({"queue_wait_ms": 150.0}, 0.0)
        d = p.decide(4, 0.0)
        # pressure 1.5: step = min(max_step, ceil(4 * 0.5)) = 2
        assert (d.direction, d.target, d.reason) == ("up", 6, "pressure")
        assert d.pressure == pytest.approx(1.5)

    def test_up_dwell_then_release(self):
        p = _policy()
        p.observe({"queue_wait_ms": 200.0}, 0.0)
        assert p.decide(2, 0.0).direction == "up"
        p.observe({"queue_wait_ms": 200.0}, 30.0)
        d = p.decide(4, 30.0)
        assert (d.direction, d.reason) == ("hold", "up-hold")
        p.observe({"queue_wait_ms": 200.0}, 61.0)
        assert p.decide(4, 61.0).direction == "up"

    def test_down_dwells_after_any_decision_then_steps_by_one(self):
        p = _policy()
        p.observe({"queue_wait_ms": 200.0}, 0.0)
        assert p.decide(2, 0.0).direction == "up"
        # idle immediately after the up: shrink dwells off the UP stamp
        p.observe({"queue_wait_ms": 10.0}, 30.0)
        d = p.decide(4, 30.0)
        assert (d.direction, d.reason) == ("hold", "down-hold")
        p.observe({"queue_wait_ms": 10.0}, 121.0)
        d = p.decide(4, 121.0)
        # shrink is drain-based: always one replica at a time
        assert (d.direction, d.target, d.reason) == ("down", 3, "idle")
        # and the next shrink dwells off the DOWN stamp
        p.observe({"queue_wait_ms": 10.0}, 180.0)
        assert p.decide(3, 180.0).reason == "down-hold"
        p.observe({"queue_wait_ms": 10.0}, 242.0)
        assert p.decide(3, 242.0).direction == "down"

    def test_at_max_and_at_min_hold(self):
        p = _policy(spec="min=2,max=4,queue_wait_ms=100")
        p.observe({"queue_wait_ms": 500.0}, 0.0)
        assert p.decide(4, 0.0).reason == "at-max"
        p.observe({"queue_wait_ms": 1.0}, 200.0)
        assert p.decide(2, 200.0).reason == "at-min"

    def test_bounds_bypass_signals_entirely(self):
        p = _policy(spec="min=2,max=4,queue_wait_ms=100")
        # no observations at all: bounds still actuate
        d = p.decide(1, 0.0)
        assert (d.direction, d.target, d.reason) == ("up", 2, "below-min-bound")
        d = p.decide(9, 500.0)
        assert (d.direction, d.target, d.reason) == ("down", 8, "above-max-bound")

    def test_slope_lookahead_fires_before_the_target_is_crossed(self):
        p = _policy()
        # 80 ms now (pressure 0.8, in-band) but ramping 1 ms/s: the
        # 60 s projection crosses the 100 ms target -> scale up EARLY
        p.observe({"queue_wait_ms": 80.0}, 0.0)
        d = p.decide(2, 0.0, slopes={"queue_wait_ms": 1.0})
        assert (d.direction, d.reason) == ("up", "slope-lookahead")
        assert d.signals["queue_wait_ms"]["projected"] == pytest.approx(1.4)

    def test_negative_slope_never_projects(self):
        p = _policy()
        p.observe({"queue_wait_ms": 80.0}, 0.0)
        d = p.decide(2, 0.0, slopes={"queue_wait_ms": -5.0})
        assert (d.direction, d.reason) == ("hold", "in-band")

    def test_none_observations_decay_to_a_hold(self):
        p = _policy()
        p.observe({"queue_wait_ms": 500.0}, 0.0)
        # counter dips / missing polls report None: they never refresh
        for t in (15.0, 30.0, 45.0):
            p.observe({"queue_wait_ms": None}, t)
        # within stale_s the last real sample still drives a decision
        assert p.decide(2, 45.0).direction == "up"
        # ... but past it the pool HOLDS instead of guessing
        d = p.decide(2, 200.0)
        assert (d.direction, d.reason) == ("hold", "no-fresh-signals")

    def test_ewma_smooths_a_single_spike(self):
        p = _policy(ewma_alpha=0.2)
        p.observe({"queue_wait_ms": 50.0}, 0.0)
        # one wild poll moves the EWMA to 50 + 0.2*(500-50) = 140...
        p.observe({"queue_wait_ms": 500.0}, 15.0)
        # ...but a policy with alpha low enough rides it out
        p2 = _policy(ewma_alpha=0.05)
        p2.observe({"queue_wait_ms": 50.0}, 0.0)
        p2.observe({"queue_wait_ms": 500.0}, 15.0)
        assert p2.decide(2, 15.0).direction == "hold"
        assert p.decide(2, 15.0).direction == "up"

    def test_role_filters_signals(self):
        spec = "min=1,max=8,ttft_p99_ms=100,itl_p99_ms=100,occupancy=0.8"
        pf = _policy(spec=spec, role="prefill")
        # an ITL surge is a DECODE signal: the prefill policy ignores it
        pf.observe({"ttft_p99_ms": 20.0, "itl_p99_ms": 900.0}, 0.0)
        assert pf.decide(2, 0.0).direction in ("hold", "down")
        de = _policy(spec=spec, role="decode")
        de.observe({"ttft_p99_ms": 900.0, "itl_p99_ms": 150.0}, 0.0)
        d = de.decide(2, 0.0)
        assert d.direction == "up"
        assert "ttft_p99_ms" not in d.signals

    def test_snapshot_carries_state(self):
        p = _policy()
        p.observe({"queue_wait_ms": 150.0}, 5.0)
        p.decide(2, 5.0)
        snap = p.snapshot()
        assert snap["role"] == "unified"
        assert snap["ewma"]["queue_wait_ms"] == pytest.approx(150.0)
        assert snap["last_up"] == 5.0 and snap["decisions"] == 1


# ---------------------------------------------------------------------------
# signal extraction off collector aggregates
# ---------------------------------------------------------------------------


class TestSignalExtraction:
    def test_windowed_p99_preferred_lifetime_fallback(self):
        dep = {"latency": {
            "ttft": {"p99_ms": 900.0, "win_p99_ms": 120.0},
            "itl": {"p99_ms": 33.0},  # no window yet: first poll
        }}
        sig = extract_signals("d", dep, window_s=60.0)
        assert sig["ttft_p99_ms"] == 120.0
        assert sig["itl_p99_ms"] == 33.0

    def test_occupancy_is_fleet_inflight_over_fleet_capacity(self):
        dep = {
            "replicas_live": 3,
            "qos": {"inflight": {"mean": 16.0},
                    "max_inflight": {"sum": 192}},
        }
        sig = extract_signals("d", dep, window_s=60.0)
        assert sig["occupancy"] == pytest.approx(48.0 / 192.0)
        # zero capacity (no live scrape) never divides
        assert extract_signals("d", {"replicas_live": 0, "qos": {}},
                               window_s=60.0)["occupancy"] is None

    def test_queue_wait_from_merged_ewma(self):
        dep = {"qos": {"queue_wait_ewma_ms": {"mean": 42.0, "max": 90.0}}}
        assert extract_signals("d", dep, window_s=60.0)[
            "queue_wait_ms"] == 42.0

    def test_shed_rate_windowed_and_dip_tolerant(self):
        h = History()
        for t, adm, shed in [(0.0, 100, 0), (30.0, 190, 10)]:
            h.record("d.admitted_total", adm, now=t)
            h.record("d.shed_total", shed, now=t)
        sig = extract_signals("d", {}, history=h, now=30.0, window_s=60.0)
        # 90 admitted + 10 shed over the window
        assert sig["shed_rate"] == pytest.approx(0.1)
        # a replica leaving rewinds the fleet sum: the dip reads as None,
        # never as a load change
        h.record("d.admitted_total", 40, now=60.0)
        h.record("d.shed_total", 12, now=60.0)
        sig = extract_signals("d", {}, history=h, now=60.0, window_s=60.0)
        assert sig["shed_rate"] is None

    def test_slopes_come_off_the_history_rings(self):
        h = History()
        for i in range(5):
            h.record("d.queue_wait_ms", 10.0 + 2.0 * i * 10.0, now=i * 10.0)
        slopes = extract_slopes("d", h, now=40.0, window_s=60.0)
        assert slopes["queue_wait_ms"] == pytest.approx(2.0, rel=0.2)
        assert slopes["ttft_p99_ms"] is None  # no such metric recorded


# ---------------------------------------------------------------------------
# endpoint diff (satellite: warm state survives scale events)
# ---------------------------------------------------------------------------


def _rec(name, *eps, **kw):
    return DeploymentRecord(
        name=name, oauth_key=f"{name}-k", oauth_secret="s",
        endpoints=tuple(Endpoint.parse(e) for e in eps), **kw)


class TestEndpointDiff:
    def test_update_reports_only_departed_replicas(self):
        d = EndpointDiff()
        assert d.removed("added", _rec("d", "a:1", "b:2")) == set()
        gone = d.removed("updated", _rec("d", "a:1", "c:3"))
        assert gone == {"b:2"}

    def test_removal_reports_the_whole_set(self):
        d = EndpointDiff()
        d.removed("added", _rec("d", "a:1", "b:2"))
        assert d.removed("removed", _rec("d", "a:1", "b:2")) == {"a:1", "b:2"}
        # and the tracking entry is gone: a re-add starts fresh
        assert d.removed("added", _rec("d", "a:1")) == set()

    def test_spec_change_detection(self):
        d = EndpointDiff()
        r1 = _rec("d", "a:1")
        assert d.spec_changed("added", r1) is True  # first sight flushes
        assert d.spec_changed("updated", r1) is False  # same hash: keep cache
        r2 = _rec("d", "a:1", annotations={"seldon.io/slo": "shed_rate=0.1"})
        assert r1.spec_hash != r2.spec_hash
        assert d.spec_changed("updated", r2) is True

    def test_seed_primes_pre_listener_records(self):
        d = EndpointDiff()
        d.seed([_rec("d", "a:1", "b:2")])
        assert d.removed("updated", _rec("d", "a:1")) == {"b:2"}
        assert d.spec_changed("updated", _rec("d", "a:1")) is True


# ---------------------------------------------------------------------------
# reconciler actuation against a FakeKube
# ---------------------------------------------------------------------------


class _FakeCollector:
    """The three surfaces the reconciler reads: merged aggregate, history
    rings, per-replica scrape payloads."""

    def __init__(self):
        self._agg = {"deployments": {}}
        self.history = History()
        self._replicas = {}

    def set_queue_wait(self, name, ms):
        self._agg["deployments"][name] = {
            "qos": {"queue_wait_ewma_ms": {"mean": ms}},
            "latency": {},
        }

    def set_digests(self, name, ep_key, hashes):
        self._replicas[(name, ep_key)] = {"payload": {"cache": {"prefix": {
            "gen": {"digest": {"hashes": list(hashes)}},
        }}}}


def _cr_obj(name="dep", endpoints="", pool=None, scale="min=1,max=8,queue_wait_ms=100"):
    ann = {AUTOSCALE_ANNOTATION: scale}
    if endpoints:
        ann[ENDPOINTS_ANNOTATION] = endpoints
    if pool:
        ann[POOL_ANNOTATION] = pool
    return {
        "apiVersion": "machinelearning.seldon.io/v1alpha2",
        "kind": "SeldonDeployment",
        "metadata": {"name": name, "namespace": "default",
                     "annotations": ann},
        "spec": {"name": name, "oauth_key": f"{name}-k",
                 "oauth_secret": "s",
                 "predictors": [{"name": "p", "graph": {
                     "name": "m", "type": "MODEL",
                     "implementation": "SIMPLE_MODEL"}}]},
    }


class _Ctl:
    def __init__(self):
        self.replica_overrides = {}


class TestReconciler:
    def _fixture(self, *eps, pool=None, scale="min=1,max=8,queue_wait_ms=100"):
        kube = FakeKube()
        store = DeploymentStore()
        ann = {AUTOSCALE_ANNOTATION: scale}
        if pool:
            ann[POOL_ANNOTATION] = pool
        store.put(_rec("dep", *eps, annotations=ann))
        col = _FakeCollector()
        ctl = _Ctl()
        rx = AutoscaleReconciler(
            kube, store, col, controller=ctl, drain_timeout_s=2.0,
            policy_overrides=dict(
                ewma_alpha=1.0, up_at=1.0, down_at=0.5, up_hold_s=0.0,
                down_hold_s=0.0, lookahead_s=0.0, max_step=2, stale_s=1e9,
            ),
        )
        return kube, store, col, ctl, rx

    def test_pool_scale_up_appends_youngest_last(self):
        kube, store, col, ctl, rx = self._fixture(
            "10.0.0.1:9000", pool="10.0.0.1:9000,10.0.0.2:9000,10.0.0.3:9000")

        async def go():
            await kube.create("SeldonDeployment", "default", _cr_obj(
                endpoints="10.0.0.1:9000",
                pool="10.0.0.1:9000,10.0.0.2:9000,10.0.0.3:9000"))
            await kube.create("Deployment", "default", {
                "metadata": {"name": "dep-p-engine", "namespace": "default"},
                "spec": {"replicas": 1}})
            col.set_queue_wait("dep", 500.0)  # pressure 5
            await rx.reconcile_once(now=100.0)
            cr = await kube.get("SeldonDeployment", "default", "dep")
            eps = cr["metadata"]["annotations"][ENDPOINTS_ANNOTATION]
            # pressure 5 at 1 replica: step clamps to max_step=2 -> 3,
            # live entry keeps slot 0, growth appends in pool order
            assert eps == "10.0.0.1:9000,10.0.0.2:9000,10.0.0.3:9000"
            wl = await kube.get("Deployment", "default", "dep-p-engine")
            assert wl["spec"]["replicas"] == 3
            assert ctl.replica_overrides["dep-p-engine"] == 3
            assert rx.scale_ups == 1 and rx.errors == 0
            assert rx.ledger[-1]["direction"] == "up"
            assert rx.ledger[-1]["outcome"] == "ok"
            snap = rx.snapshot()
            assert snap["deployments"]["dep"]["last"]["target"] == 3

        run(go())

    def test_exhausted_pool_reports_instead_of_scaling(self):
        kube, store, col, ctl, rx = self._fixture(
            "10.0.0.1:9000", pool="10.0.0.1:9000")

        async def go():
            await kube.create("SeldonDeployment", "default", _cr_obj(
                endpoints="10.0.0.1:9000", pool="10.0.0.1:9000"))
            col.set_queue_wait("dep", 500.0)
            await rx.reconcile_once(now=100.0)
            assert rx.scale_ups == 0
            assert rx.snapshot()["deployments"]["dep"]["last"][
                "reason"] == "pool-exhausted"

        run(go())

    def test_victim_is_coldest_then_youngest_peer_is_warmest(self):
        _, _, col, _, rx = self._fixture("a:1", "b:2", "c:3")
        col.set_digests("dep", "a:1", ["h1", "h2", "h3"])
        col.set_digests("dep", "b:2", ["h1"])
        col.set_digests("dep", "c:3", ["h4"])
        rec = rx.store.get("dep-k")
        victim, peer, counts = rx._pick_victim_and_peer(rec)
        # b and c tie at 1 digest: the YOUNGER (higher index) drains
        assert victim.key == "c:3"
        assert peer.key == "a:1"  # warmest survivor absorbs the streams
        assert counts == {"a:1": 3, "b:2": 1, "c:3": 1}

    def test_drain_failure_aborts_the_shrink(self):
        async def go():
            refusals = []

            async def refuse(request):
                refusals.append(await request.json())
                return web.json_response({"migrated": 0, "failed": 1},
                                         status=200)

            app = web.Application()
            app.router.add_post("/admin/drain", refuse)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = runner.addresses[0][1]
            try:
                kube, store, col, ctl, rx = self._fixture(
                    f"127.0.0.1:{port}", "10.9.9.9:9000")
                await kube.create("SeldonDeployment", "default", _cr_obj(
                    endpoints=f"127.0.0.1:{port},10.9.9.9:9000"))
                # make the live stub the victim: zero digests, youngest
                col.set_digests("dep", "10.9.9.9:9000", ["h1"])
                store.put(_rec(
                    "dep", "10.9.9.9:9000", f"127.0.0.1:{port}",
                    annotations={AUTOSCALE_ANNOTATION:
                                 "min=1,max=8,queue_wait_ms=100"}))
                col.set_queue_wait("dep", 10.0)  # idle: pressure 0.1
                await rx.reconcile_once(now=100.0)
                # the drain refused: the victim keeps serving, nothing
                # was patched, and the ledger records the abort
                assert rx.drain_failures == 1 and rx.scale_downs == 0
                assert refusals and refusals[0]["peer"] == "10.9.9.9:9000"
                cr = await kube.get("SeldonDeployment", "default", "dep")
                assert "10.9.9.9" in cr["metadata"]["annotations"][
                    ENDPOINTS_ANNOTATION]
                assert rx.ledger[-1]["outcome"] == "drain-failed"
            finally:
                await rx.stop()
                await runner.cleanup()

        run(go())

    def test_unreachable_victim_aborts_the_shrink(self):
        async def go():
            kube, store, col, ctl, rx = self._fixture(
                "127.0.0.1:1", "127.0.0.1:2")  # nothing listens there
            await kube.create("SeldonDeployment", "default", _cr_obj(
                endpoints="127.0.0.1:1,127.0.0.1:2"))
            col.set_queue_wait("dep", 10.0)
            await rx.reconcile_once(now=100.0)
            assert rx.drain_failures == 1 and rx.scale_downs == 0
            assert rx.ledger[-1]["drain"]["status"] == 0
            await rx.stop()

        run(go())

    def test_ledger_ring_is_bounded(self):
        kube = FakeKube()
        rx = AutoscaleReconciler(
            kube, DeploymentStore(), _FakeCollector(), ledger_size=4)
        for i in range(10):
            rx._ledger_entry({"i": i})
        assert [e["i"] for e in rx.ledger] == [6, 7, 8, 9]

    def test_malformed_default_spec_surfaces_not_raises(self):
        kube, store, col, ctl, rx = self._fixture(
            "a:1", scale="min=0,warp=9")

        async def go():
            await rx.reconcile_once(now=1.0)
            assert rx.errors == 0
            assert "error" in rx.snapshot()["deployments"]["dep"]["last"]

        run(go())

    def test_departed_deployment_prunes_policy_state(self):
        kube, store, col, ctl, rx = self._fixture("a:1")

        async def go():
            col.set_queue_wait("dep", 500.0)
            await rx.reconcile_once(now=1.0)
            assert "dep" in rx._policies
            store.remove("dep-k")
            await rx.reconcile_once(now=2.0)
            assert rx._policies == {}

        run(go())


# ---------------------------------------------------------------------------
# idempotent POST /admin/drain over a live generative engine
# ---------------------------------------------------------------------------


PREDICTOR = {
    "name": "llm",
    "graph": {
        "name": "gen",
        "type": "MODEL",
        "implementation": "JAX_GENERATIVE",
        "parameters": [
            {"name": "family", "value": "llama", "type": "STRING"},
            {"name": "preset", "value": "tiny", "type": "STRING"},
            {"name": "n_slots", "value": "2", "type": "INT"},
        ],
    },
}


class TestDrainIdempotency:
    # boots real generative engines (one JAX compile each) — excluded from
    # the tier-1 `-m 'not slow'` sweep; `make scale-check` runs the full file
    pytestmark = pytest.mark.slow

    def test_repeat_drain_conflicts_with_state_undrain_races_refused(self):
        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.engine.app import EngineApp
        from seldon_core_tpu.engine.service import PredictionService
        from seldon_core_tpu.graph.spec import PredictorSpec

        async def go():
            service = PredictionService(
                PredictorSpec.model_validate(PREDICTOR))
            engine = EngineApp(service)
            client = TestClient(TestServer(engine.build()))
            await client.start_server()
            try:
                for _ in range(600):
                    if (await client.get("/ready")).status == 200:
                        break
                    await asyncio.sleep(0.05)
                (unit,) = service.generative_units()
                sched = unit.scheduler

                # gate the quiesce so the drain stays observably in-flight
                gate = asyncio.Event()
                entered = asyncio.Event()
                orig = sched.drain_wait_quiesced

                async def gated(timeout_s):
                    entered.set()
                    await gate.wait()
                    return await orig(timeout_s)

                sched.drain_wait_quiesced = gated
                first = asyncio.ensure_future(
                    client.post("/admin/drain", json={}))
                await asyncio.wait_for(entered.wait(), 10)

                # a REPEAT while in flight answers 409 with the live
                # phase — the reconciler's retry reads progress, not a
                # bare refusal
                r = await client.post("/admin/drain", json={})
                assert r.status == 409
                body = await r.json()
                assert body["drain"]["phase"] == "quiescing"
                assert "elapsed_ms" in body["drain"]

                # undrain mid-quiesce is REFUSED: lifting it here would
                # fork streams a peer may already be continuing
                r = await client.post("/admin/undrain")
                assert r.status == 409
                assert "in flight" in (await r.json())["status"]["info"]

                gate.set()
                resp = await asyncio.wait_for(first, 30)
                assert resp.status == 200
                out = await resp.json()
                assert out["quiesced"] is True and out["peer"] is None

                # the no-peer drain PARKS: a repeat still conflicts, now
                # reporting the parked phase
                r = await client.post("/admin/drain", json={})
                assert r.status == 409
                assert (await r.json())["drain"]["phase"] == "parked"

                # ... and THIS is the state undrain exists for
                sched.drain_wait_quiesced = orig
                r = await client.post("/admin/undrain")
                assert r.status == 200
                assert (await r.json())["resuming"] is True

                # fully lifted: a fresh drain cycle works again
                r = await client.post("/admin/drain", json={})
                assert r.status == 200
                r = await client.post("/admin/undrain")
                assert r.status == 200

                # nothing draining: undrain is a 409, not a silent no-op
                r = await client.post("/admin/undrain")
                assert r.status == 409
            finally:
                await client.close()

        run(go())

    def test_idle_engine_drains_immediately(self):
        """An idle victim (no run loop alive) must quiesce at once, not
        sit out the full timeout — the autoscaler's common shrink case."""
        import time

        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.engine.app import EngineApp
        from seldon_core_tpu.engine.service import PredictionService
        from seldon_core_tpu.graph.spec import PredictorSpec

        async def go():
            service = PredictionService(
                PredictorSpec.model_validate(PREDICTOR))
            engine = EngineApp(service)
            client = TestClient(TestServer(engine.build()))
            await client.start_server()
            try:
                for _ in range(600):
                    if (await client.get("/ready")).status == 200:
                        break
                    await asyncio.sleep(0.05)
                t0 = time.perf_counter()
                r = await client.post("/admin/drain",
                                      json={"timeout_s": 30})
                took = time.perf_counter() - t0
                assert r.status == 200
                assert (await r.json())["quiesced"] is True
                assert took < 5.0, f"idle drain took {took:.1f}s"
                r = await client.post("/admin/undrain")
                assert r.status == 200
            finally:
                await client.close()

        run(go())

    def test_scheduler_level_drain_still_undrainable(self):
        """A drain begun OUTSIDE the HTTP handler (chaos harness, tests)
        has no handler state; undrain must still lift it."""
        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.engine.app import EngineApp
        from seldon_core_tpu.engine.service import PredictionService
        from seldon_core_tpu.graph.spec import PredictorSpec

        async def go():
            service = PredictionService(
                PredictorSpec.model_validate(PREDICTOR))
            engine = EngineApp(service)
            client = TestClient(TestServer(engine.build()))
            await client.start_server()
            try:
                for _ in range(600):
                    if (await client.get("/ready")).status == 200:
                        break
                    await asyncio.sleep(0.05)
                (unit,) = service.generative_units()
                unit.scheduler.drain_begin()
                # the handler synthesizes a parked view for the repeat...
                r = await client.post("/admin/drain", json={})
                assert r.status == 409
                assert (await r.json())["drain"]["phase"] == "parked"
                # ...and undrain lifts it
                r = await client.post("/admin/undrain")
                assert r.status == 200
            finally:
                await client.close()

        run(go())


class TestSchedulerLoopTurnover:
    # boots a real generative model — slow-marked like TestDrainIdempotency
    pytestmark = pytest.mark.slow

    def test_component_survives_short_lived_event_loops(self):
        """A component driven through several ``asyncio.run`` loops (CLI
        tools, the loadtest harness, per-call test helpers) must not crash
        at close: the scheduler's run-loop task is respawned per loop, and
        its wake event must bind to the CURRENT loop — a stale event from
        a dead loop makes the idle park raise a cross-loop RuntimeError
        that ``close()`` then re-raises."""
        import jax

        from seldon_core_tpu.contract.payload import DataKind, Payload
        from seldon_core_tpu.executor.generation import (
            GenerativeComponent,
            GenerativeModel,
        )
        from seldon_core_tpu.models import llama

        cfg = llama.Config.tiny(max_seq=64)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        comp = GenerativeComponent(
            GenerativeModel(cfg, params, n_slots=2, decode_block=4),
            max_new_tokens=4,
        )
        payload = Payload(
            json.dumps({"tokens": [5, 9, 2]}), [], DataKind.STRING, None
        )

        async def ask_and_idle():
            out = json.loads((await comp.predict_raw(payload)).data)["tokens"]
            # spin enough turns for the run loop to reach its fully-idle
            # park on THIS loop before asyncio.run tears the loop down —
            # the park is where a stale cross-loop event would kill it
            for _ in range(200):
                await asyncio.sleep(0)
            return out

        first = asyncio.run(ask_and_idle())
        second = asyncio.run(ask_and_idle())
        assert first == second  # greedy decode is loop-agnostic
        asyncio.run(comp.close())


# ---------------------------------------------------------------------------
# kubesim e2e: the diurnal day and role independence
# ---------------------------------------------------------------------------


class ElasticStub:
    """A fake engine replica for the autoscale loop: mutable qos + stage
    histograms on ``/stats/summary`` and a recording ``/admin/drain``."""

    def __init__(self):
        self.qos = {
            "admitted_total": 0, "shed_total": 0,
            "deadline_miss_total": 0, "queue_wait_ewma_ms": 1.0,
            "inflight": 0, "predicted_completion_ms": 1.0,
            "max_inflight": 64, "max_queue": 128,
            "shed_by_reason": {}, "brownout": {"active": False},
        }
        self.stage_hist = {}
        self.drain_calls = []
        self.runner = None
        self.port = None

    async def start(self):
        app = web.Application()
        app.router.add_get("/stats/summary", self._summary)
        app.router.add_post("/admin/drain", self._drain)
        self.runner = web.AppRunner(app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        self.port = self.runner.addresses[0][1]
        return self

    async def stop(self):
        if self.runner is not None:
            await self.runner.cleanup()
            self.runner = None

    async def _summary(self, request):
        return web.json_response({
            "qos": self.qos, "breakdown": {}, "cache": {},
            "wire": {}, "stage_hist": self.stage_hist,
        })

    async def _drain(self, request):
        self.drain_calls.append(await request.json())
        return web.json_response(
            {"quiesced": True, "migrated": 1, "failed": 0, "parked": 0})

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"


_FAST_POLICY = dict(
    ewma_alpha=1.0, up_at=1.0, down_at=0.5, up_hold_s=0.0,
    down_hold_s=0.0, lookahead_s=0.0, max_step=2, stale_s=1e9,
)


async def _settle(pred, timeout=5.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if pred():
            return
        await asyncio.sleep(0.02)
    raise AssertionError("condition never settled")


def _elastic_cr(name, endpoints, pool, scale, role=None):
    from seldon_core_tpu.gateway.watch import CR_KIND

    ann = {
        ENDPOINTS_ANNOTATION: endpoints,
        POOL_ANNOTATION: pool,
        AUTOSCALE_ANNOTATION: scale,
    }
    if role:
        ann["seldon.io/engine-role"] = role
    return {
        "apiVersion": "machinelearning.seldon.io/v1alpha2",
        "kind": CR_KIND,
        "metadata": {"name": name, "namespace": "default",
                     "annotations": ann},
        "spec": {"name": name, "oauth_key": f"{name}-k",
                 "oauth_secret": "s",
                 "predictors": [{"name": "p", "graph": {
                     "name": "m", "type": "MODEL",
                     "implementation": "SIMPLE_MODEL"}}]},
    }


class TestKubesimElasticE2E:
    def test_diurnal_day_one_to_n_to_one_zero_drops(self):
        """Load triples, the pool follows it up 1 -> 3, the ebb drains
        it back 3 -> 2 -> 1 — every shrink preceded by a successful
        drain (zero dropped streams) and the response-cache-bearing
        spec hash NEVER rolling across any scale event."""
        from seldon_core_tpu.gateway.watch import CR_KIND, GatewayWatcher
        from seldon_core_tpu.obs.fleet import FleetCollector
        from seldon_core_tpu.operator.kube_http import HttpKube
        from seldon_core_tpu.testing.kubesim import KubeSim

        async def go(sim):
            stubs = [await ElasticStub().start() for _ in range(3)]
            kube = HttpKube(base_url=sim.base_url)
            store = DeploymentStore()
            watcher = GatewayWatcher(kube, store, resync_s=999.0)
            col = FleetCollector(store, interval_s=10.0, jitter=0.0)
            rx = AutoscaleReconciler(
                kube, store, col, drain_timeout_s=5.0,
                policy_overrides=_FAST_POLICY)
            try:
                await watcher.start()
                pool = ",".join(s.addr for s in stubs)
                await kube.create(CR_KIND, "default", _elastic_cr(
                    "elastic", stubs[0].addr, pool,
                    "min=1,max=3,queue_wait_ms=100"))
                await _settle(lambda: store.get("elastic-k") is not None)
                hash0 = store.get("elastic-k").spec_hash

                # --- morning surge: queue wait triples past the target
                for s in stubs:
                    s.qos["queue_wait_ewma_ms"] = 500.0
                await col.poll_once(now=10.0)
                await rx.reconcile_once(now=10.0)
                await _settle(lambda: len(
                    store.get("elastic-k").replica_endpoints) == 3)
                rec = store.get("elastic-k")
                # growth appended pool order: youngest is LAST
                assert [e.key for e in rec.replica_endpoints] == \
                    [s.addr for s in stubs]
                assert rec.spec_hash == hash0  # cache survives the grow
                assert rx.scale_ups == 1

                # --- at max, pressure still high: hold, not thrash
                await col.poll_once(now=20.0)
                await rx.reconcile_once(now=20.0)
                assert rx.snapshot()["deployments"]["elastic"]["last"][
                    "reason"] == "at-max"

                # --- evening ebb: two drain-based shrinks back to 1
                for s in stubs:
                    s.qos["queue_wait_ewma_ms"] = 10.0
                await col.poll_once(now=30.0)
                await rx.reconcile_once(now=30.0)
                await _settle(lambda: len(
                    store.get("elastic-k").replica_endpoints) == 2)
                await col.poll_once(now=40.0)
                await rx.reconcile_once(now=40.0)
                await _settle(lambda: len(
                    store.get("elastic-k").replica_endpoints) == 1)

                rec = store.get("elastic-k")
                assert rec.spec_hash == hash0  # ...and both shrinks
                assert rx.scale_downs == 2 and rx.drain_failures == 0
                # zero dropped streams: every departed replica was
                # drained exactly once, toward a surviving peer
                survivors = {e.key for e in rec.replica_endpoints}
                drained = [s for s in stubs if s.addr not in survivors]
                assert len(drained) == 2
                for s in drained:
                    assert len(s.drain_calls) == 1
                    assert s.drain_calls[0]["peer"] in \
                        {x.addr for x in stubs} - {s.addr}
                # the survivor never saw a drain
                (kept,) = [s for s in stubs if s.addr in survivors]
                assert kept.drain_calls == []
                # steady state: a further tick holds at min
                await col.poll_once(now=50.0)
                await rx.reconcile_once(now=50.0)
                assert rx.snapshot()["deployments"]["elastic"]["last"][
                    "reason"] == "at-min"
                # the ledger tells the whole day's story
                dirs = [e["direction"] for e in rx.ledger]
                assert dirs == ["up", "down", "down"]
            finally:
                await rx.stop()
                await col.stop()
                await watcher.stop()
                await kube.close()
                for s in stubs:
                    await s.stop()

        from seldon_core_tpu.testing.kubesim import KubeSim as _KS
        with _KS() as sim:
            run(go(sim))

    def test_roles_scale_independently(self):
        """A TTFT surge moves the PREFILL pool and leaves decode flat;
        an ITL surge then moves only DECODE."""
        from seldon_core_tpu.gateway.watch import CR_KIND, GatewayWatcher
        from seldon_core_tpu.obs.fleet import FleetCollector
        from seldon_core_tpu.operator.kube_http import HttpKube
        from seldon_core_tpu.testing.kubesim import KubeSim

        def _count(store, key):
            rec = store.get(key)
            return len(rec.replica_endpoints) if rec else 0

        async def go(sim):
            pf = [await ElasticStub().start() for _ in range(2)]
            de = [await ElasticStub().start() for _ in range(2)]
            kube = HttpKube(base_url=sim.base_url)
            store = DeploymentStore()
            watcher = GatewayWatcher(kube, store, resync_s=999.0)
            col = FleetCollector(store, interval_s=10.0, jitter=0.0)
            rx = AutoscaleReconciler(
                kube, store, col, drain_timeout_s=5.0,
                policy_overrides=_FAST_POLICY)
            try:
                await watcher.start()
                await kube.create(CR_KIND, "default", _elastic_cr(
                    "pf", pf[0].addr, ",".join(s.addr for s in pf),
                    "min=1,max=2,ttft_p99_ms=250", role="prefill"))
                await kube.create(CR_KIND, "default", _elastic_cr(
                    "de", de[0].addr, ",".join(s.addr for s in de),
                    "min=1,max=2,itl_p99_ms=40", role="decode"))
                await _settle(lambda: store.get("pf-k") is not None
                              and store.get("de-k") is not None)

                # both stages healthy on the first poll (establishes the
                # window baseline), then TTFT surges on the second
                pf[0].stage_hist = {"ttft": bin_samples([0.1] * 50)}
                de[0].stage_hist = {"itl": bin_samples([0.005] * 50)}
                await col.poll_once(now=10.0)
                pf[0].stage_hist = {
                    "ttft": bin_samples([0.1] * 50 + [0.6] * 200)}
                de[0].stage_hist = {"itl": bin_samples([0.005] * 100)}
                await col.poll_once(now=20.0)
                await rx.reconcile_once(now=20.0)
                await _settle(lambda: _count(store, "pf-k") == 2)
                # ITL stayed flat: decode did NOT move
                assert _count(store, "de-k") == 1
                assert rx.scale_ups == 1

                # vice versa: TTFT cools into the band, ITL surges
                pf[0].stage_hist = {
                    "ttft": bin_samples([0.1] * 50 + [0.6] * 200
                                        + [0.2] * 400)}
                pf[1].stage_hist = {"ttft": bin_samples([0.2] * 400)}
                de[0].stage_hist = {
                    "itl": bin_samples([0.005] * 100 + [0.1] * 200)}
                await col.poll_once(now=30.0)
                await rx.reconcile_once(now=30.0)
                await _settle(lambda: _count(store, "de-k") == 2)
                # the prefill pool held: in-band TTFT is not a reason
                # to move in either direction
                assert _count(store, "pf-k") == 2
                assert rx.drain_failures == 0
            finally:
                await rx.stop()
                await col.stop()
                await watcher.stop()
                await kube.close()
                for s in pf + de:
                    await s.stop()

        from seldon_core_tpu.testing.kubesim import KubeSim as _KS
        with _KS() as sim:
            run(go(sim))


# ---------------------------------------------------------------------------
# the gateway surface: /stats/autoscale
# ---------------------------------------------------------------------------


class TestStatsSurface:
    def test_disabled_gateway_reports_disabled(self):
        from seldon_core_tpu.gateway.app import GatewayApp

        async def go():
            from aiohttp.test_utils import TestClient, TestServer

            store = DeploymentStore()
            gw = GatewayApp(store)
            client = TestClient(TestServer(gw.build()))
            await client.start_server()
            try:
                resp = await client.get("/stats/autoscale")
                assert resp.status == 200
                body = await resp.json()
                assert body["autoscale"] == {"enabled": False}
            finally:
                await client.close()

        run(go())

    def test_wired_reconciler_snapshot_served(self):
        from seldon_core_tpu.gateway.app import GatewayApp

        async def go():
            from aiohttp.test_utils import TestClient, TestServer

            store = DeploymentStore()
            gw = GatewayApp(store)
            gw.autoscaler = AutoscaleReconciler(
                FakeKube(), store, _FakeCollector(), ledger_size=8)
            gw.autoscaler._ledger_entry({"direction": "up"})
            client = TestClient(TestServer(gw.build()))
            await client.start_server()
            try:
                body = await (await client.get("/stats/autoscale")).json()
                assert body["autoscale"]["enabled"] is True
                assert body["autoscale"]["ledger"] == [{"direction": "up"}]
            finally:
                await client.close()

        run(go())
