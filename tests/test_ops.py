"""Pallas kernels: flash attention pinned to the dense reference.

Runs in interpret mode on the CPU harness (the same kernel compiles for
real TPU; tested there manually — the wire benches exercise it via
seq_impl=flash)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seldon_core_tpu.ops import flash_attention


def _dense(q, k, v, causal):
    D = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    if causal:
        S, Sk = q.shape[2], k.shape[2]
        mask = jnp.arange(S)[:, None] >= jnp.arange(Sk)[None, :]
        s = jnp.where(mask, s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


class TestFlashAttention:
    @pytest.mark.parametrize("shape", [(1, 2, 128, 64), (2, 4, 256, 32), (1, 1, 64, 128)])
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, shape, causal):
        B, H, S, D = shape
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=shape), jnp.float32)
        k = jnp.asarray(rng.normal(size=shape), jnp.float32)
        v = jnp.asarray(rng.normal(size=shape), jnp.float32)
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_dense(q, k, v, causal)), rtol=2e-5, atol=2e-5
        )

    def test_multi_block_accumulation(self):
        """More key blocks than query blocks: the online-softmax recurrence
        must rescale across every key tile."""
        B, H, S, D = 1, 2, 512, 64
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(B, H, S, D)) * 3, jnp.float32)  # big logits
        k = jnp.asarray(rng.normal(size=(B, H, S, D)) * 3, jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, H, S, D)), jnp.float32)
        out = flash_attention(q, k, v, causal=True, block_q=128, block_k=64)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_dense(q, k, v, True)), rtol=2e-4, atol=2e-4
        )

    def test_indivisible_seq_rejected(self):
        q = jnp.zeros((1, 1, 100, 64), jnp.float32)
        with pytest.raises(ValueError, match="divisible"):
            flash_attention(q, q, q, block_q=64, block_k=64)


class TestFlashBlhdAdapter:
    """Direct unit coverage for ``flash_causal_attention_blhd`` — the
    model-zoo entry (``seq_impl=flash``) — against the dense reference
    (``models/llama.py::_dense_causal_attention``), across sequence
    lengths that are NOT multiples of the preferred 128 tile and across
    GQA head counts (the adapter receives kv already repeated to full
    heads, exactly as ``_layer`` calls it)."""

    def _ref(self, q, k, v):
        from seldon_core_tpu.models.llama import _dense_causal_attention

        return _dense_causal_attention(q, k, v)

    @pytest.mark.parametrize("seq", [48, 96, 120, 192])
    def test_matches_dense_at_non_multiple_of_block_lengths(self, seq):
        from seldon_core_tpu.ops import flash_causal_attention_blhd

        B, H, D = 2, 4, 32
        rng = np.random.default_rng(seq)
        q = jnp.asarray(rng.normal(size=(B, seq, H, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, seq, H, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, seq, H, D)), jnp.float32)
        out = flash_causal_attention_blhd(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._ref(q, k, v)),
            rtol=2e-5, atol=2e-5,
        )

    @pytest.mark.parametrize("n_heads,n_kv", [(8, 2), (4, 1), (6, 3)])
    def test_matches_dense_across_gqa_head_counts(self, n_heads, n_kv):
        from seldon_core_tpu.models.llama import _gqa_repeat
        from seldon_core_tpu.ops import flash_causal_attention_blhd

        B, S, D = 1, 80, 16
        rng = np.random.default_rng(n_heads * 10 + n_kv)
        q = jnp.asarray(rng.normal(size=(B, S, n_heads, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, n_kv, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, n_kv, D)), jnp.float32)
        kf, vf = _gqa_repeat(k, n_heads), _gqa_repeat(v, n_heads)
        out = flash_causal_attention_blhd(q, kf, vf)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._ref(q, kf, vf)),
            rtol=2e-5, atol=2e-5,
        )

    def test_fit_block_picks_largest_divisor(self):
        from seldon_core_tpu.ops.flash_attention import _fit_block

        assert _fit_block(128) == 128
        assert _fit_block(192) == 96
        assert _fit_block(48) == 48
        assert _fit_block(120) == 120
        assert _fit_block(97) == 97  # <= preferred: one tile, never rejects
        assert _fit_block(131) == 1  # prime past the tile: degrades


class TestFlashInLlama:
    def test_forward_seq_impl_flash_matches_dense(self):
        from seldon_core_tpu.models import llama

        cfg = llama.Config.tiny(max_seq=64)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        toks = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 64)), jnp.int32
        )
        dense = llama.forward(params, toks, cfg, seq_impl="dense")
        flash = llama.forward(params, toks, cfg, seq_impl="flash")
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(flash), rtol=5e-4, atol=5e-4
        )

    def test_generative_flash_matches_reference(self):
        from seldon_core_tpu.executor.generation import GenerativeModel
        from seldon_core_tpu.models import llama

        cfg = llama.Config.tiny(max_seq=64)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        prompt = np.array([5, 9, 2, 17, 3], np.int32)
        # reference: dense full-forward greedy loop
        toks = list(prompt)
        for _ in range(4):
            logits = llama.forward(
                params, jnp.asarray([toks], jnp.int32), cfg, seq_impl="dense"
            )
            toks.append(int(jnp.argmax(logits[0, -1])))
        expected = toks[len(prompt):]

        model = GenerativeModel(cfg, params, n_slots=1, seq_impl="flash", decode_block=4)
        first = model.admit(0, prompt, 0.0, 0)
        got = [first]
        cur = np.array([first], np.int32)
        toks_seq, act_seq = model.step_k(
            cur,
            np.array([True]),
            np.zeros(1, np.float32),
            0,
            np.array([-1], np.int32),
            np.array([3], np.int32),
            3,
        )
        for i in range(3):
            if act_seq[i, 0]:
                got.append(int(toks_seq[i, 0]))
        assert got == expected


class TestPagedDecodeAttention:
    """Paged decode-attention kernel (docs/PERFORMANCE.md §7) pinned to its
    pure-JAX reference — the exact math ``_decode_paged_multi``'s XLA
    gather path runs — across query counts (plain step and speculative
    verify), positions that are NOT multiples of the KV block size, GQA
    head counts, and the int8 dequant-fusion path."""

    def _rand(self, rng, *shape):
        return jnp.asarray(rng.standard_normal(shape), jnp.float32)

    def _compare(self, S, L, KV, G, D, NB, BS, WB, *, quant=False, seed=0):
        from seldon_core_tpu.ops import (
            paged_decode_attention,
            paged_decode_attention_reference,
        )

        rng = np.random.default_rng(seed)
        H = KV * G
        q = self._rand(rng, S, L, H, D)
        table = jnp.asarray(rng.integers(0, NB, (S, WB)), jnp.int32)
        # positions deliberately off block boundaries
        pos = jnp.asarray(rng.integers(0, WB * BS - L, S), jnp.int32)
        kw = {}
        if quant:
            k = jnp.asarray(
                rng.integers(-127, 128, (NB, BS, KV, D)), jnp.int8
            )
            v = jnp.asarray(
                rng.integers(-127, 128, (NB, BS, KV, D)), jnp.int8
            )
            kw["k_scale"] = jnp.asarray(
                rng.random((NB, BS, KV)) * 0.1, jnp.float32
            )
            kw["v_scale"] = jnp.asarray(
                rng.random((NB, BS, KV)) * 0.1, jnp.float32
            )
        else:
            k = self._rand(rng, NB, BS, KV, D)
            v = self._rand(rng, NB, BS, KV, D)
        out = paged_decode_attention(q, k, v, table, pos, **kw)
        ref = paged_decode_attention_reference(q, k, v, table, pos, **kw)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    @pytest.mark.parametrize("L", [1, 3, 5])
    def test_matches_reference_across_query_counts(self, L):
        self._compare(3, L, 2, 2, 16, 9, 16, 3, seed=L)

    @pytest.mark.parametrize("KV,G", [(1, 4), (2, 2), (3, 2), (4, 1)])
    def test_matches_reference_across_gqa_head_counts(self, KV, G):
        self._compare(2, 2, KV, G, 16, 7, 8, 3, seed=KV * 10 + G)

    @pytest.mark.parametrize("BS,WB", [(4, 7), (16, 2), (8, 5)])
    def test_matches_reference_at_non_multiple_positions(self, BS, WB):
        # pos values land mid-block; the mask must cut inside a KV block
        self._compare(4, 2, 2, 2, 8, 11, BS, WB, seed=BS)

    def test_int8_dequant_fusion_matches_reference(self):
        self._compare(3, 2, 2, 2, 16, 9, 16, 3, quant=True)
        self._compare(2, 1, 2, 4, 8, 5, 4, 4, quant=True, seed=7)

    def test_zero_position_first_token(self):
        # pos = 0 everywhere: only row 0 of block table[ :, 0] is visible
        from seldon_core_tpu.ops import (
            paged_decode_attention,
            paged_decode_attention_reference,
        )

        rng = np.random.default_rng(3)
        q = self._rand(rng, 2, 1, 4, 8)
        k = self._rand(rng, 5, 4, 2, 8)
        v = self._rand(rng, 5, 4, 2, 8)
        table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
        pos = jnp.zeros(2, jnp.int32)
        out = paged_decode_attention(q, k, v, table, pos)
        ref = paged_decode_attention_reference(q, k, v, table, pos)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )
        # with one visible row, attention must return exactly that row's v
        np.testing.assert_allclose(
            np.asarray(out[0, 0, 0]), np.asarray(v[1, 0, 0]),
            rtol=2e-5, atol=2e-5,
        )

    def test_in_model_decode_matches_dense_path(self):
        """The kernel call site inside ``decode_slots_paged``: one decode
        step with kernel on equals the XLA gather path bit-for-bit-ish
        (same fp32 accumulation; interpret mode on CPU)."""
        from seldon_core_tpu.models import llama

        cfg = llama.Config.tiny(max_seq=64)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        for kv_dtype in (None, "int8"):
            cache = llama.init_paged_cache(cfg, 2, 9, 16, kv_dtype=kv_dtype)
            row = np.zeros(4, np.int32)
            row[:4] = np.arange(1, 5)
            logits, cache = llama.prefill_slot_paged(
                params,
                jnp.asarray(np.arange(1, 17)[None, :], jnp.int32),
                jnp.int32(16), jnp.int32(0), jnp.asarray(row), cache, cfg,
            )
            tok = jnp.asarray([int(jnp.argmax(logits)), 0], jnp.int32)
            act = jnp.asarray([True, False])
            dense_logits, _ = llama.decode_slots_paged(
                params, tok, dict(cache), act, cfg, window=64, kernel=False
            )
            kern_logits, _ = llama.decode_slots_paged(
                params, tok, dict(cache), act, cfg, window=64, kernel=True
            )
            np.testing.assert_allclose(
                np.asarray(dense_logits[0]), np.asarray(kern_logits[0]),
                rtol=2e-5, atol=2e-5,
            )
