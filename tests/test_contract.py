"""Contract-layer tests: JSON/proto round-trips and typed parameters.

Mirrors the reference's proto round-trip suite
(reference: engine/src/test/java/io/seldon/engine/pb/TestPredictionProto.java,
TestMatrixOps.java) plus the rawTensor extension.
"""

import base64
import json

import numpy as np
import pytest

from seldon_core_tpu.contract import (
    CodecError,
    DataKind,
    Meta,
    ParameterError,
    Payload,
    encode_parameters,
    feedback_from_dict,
    feedback_to_dict,
    parse_parameters,
    payload_from_dict,
    payload_from_json,
    payload_from_proto,
    payload_to_dict,
    payload_to_json,
    payload_to_proto,
)


class TestJsonCodec:
    def test_tensor_round_trip(self):
        msg = {
            "meta": {"puid": "abc123"},
            "data": {"names": ["f0", "f1"], "tensor": {"shape": [2, 2], "values": [1, 2, 3, 4]}},
        }
        p = payload_from_dict(msg)
        assert p.kind == DataKind.TENSOR
        assert p.names == ["f0", "f1"]
        assert p.meta.puid == "abc123"
        np.testing.assert_array_equal(p.array, [[1.0, 2.0], [3.0, 4.0]])

        out = payload_to_dict(p)
        assert out["data"]["tensor"]["shape"] == [2, 2]
        assert out["data"]["tensor"]["values"] == [1.0, 2.0, 3.0, 4.0]
        assert out["meta"]["puid"] == "abc123"

    def test_ndarray_round_trip(self):
        msg = {"data": {"ndarray": [[1.0, 2.0], [3.0, 4.0]]}}
        p = payload_from_dict(msg)
        assert p.kind == DataKind.NDARRAY
        out = payload_to_dict(p)
        assert out["data"]["ndarray"] == [[1.0, 2.0], [3.0, 4.0]]

    def test_encoding_preserved_through_transform(self):
        # Reference preserves ndarray-vs-tensor across node updates
        # (PredictorUtils.java:107-127).
        p = payload_from_dict({"data": {"tensor": {"shape": [1, 2], "values": [1, 2]}}})
        p2 = p.with_array(np.array([[9.0, 9.0]]))
        assert p2.kind == DataKind.TENSOR
        assert "tensor" in payload_to_dict(p2)["data"]

    def test_bin_and_str_data(self):
        raw = b"\x00\x01binary"
        p = payload_from_dict({"binData": base64.b64encode(raw).decode()})
        assert p.kind == DataKind.BINARY and p.data == raw
        assert base64.b64decode(payload_to_dict(p)["binData"]) == raw

        p = payload_from_dict({"strData": "hello"})
        assert p.kind == DataKind.STRING and p.data == "hello"
        assert payload_to_dict(p)["strData"] == "hello"

    def test_raw_tensor_float32(self):
        arr = np.arange(6, dtype=np.float32).reshape(2, 3)
        p = Payload.from_array(arr, names=["x"], kind=DataKind.RAW)
        d = payload_to_dict(p)
        assert d["rawTensor"]["dtype"] == "float32"
        p2 = payload_from_dict(json.loads(json.dumps(d)))
        assert p2.array.dtype == np.float32
        np.testing.assert_array_equal(p2.array, arr)

    def test_raw_tensor_bfloat16(self):
        import ml_dtypes

        arr = np.asarray([[1.5, -2.25]], dtype=ml_dtypes.bfloat16)
        p = Payload.from_array(arr, kind=DataKind.RAW)
        d = payload_to_dict(p)
        assert d["rawTensor"]["dtype"] == "bfloat16"
        p2 = payload_from_dict(d)
        np.testing.assert_array_equal(
            p2.array.astype(np.float32), arr.astype(np.float32)
        )

    def test_json_string_round_trip(self):
        p = Payload.from_array(np.eye(2), names=["a", "b"], kind=DataKind.TENSOR)
        p.meta.puid = "p1"
        p.meta.tags["v"] = "canary"
        p2 = payload_from_json(payload_to_json(p))
        np.testing.assert_array_equal(p2.array, np.eye(2))
        assert p2.meta.tags == {"v": "canary"}

    def test_errors(self):
        with pytest.raises(CodecError):
            payload_from_json(b"{not json")
        with pytest.raises(CodecError):
            payload_from_dict({"data": {}})
        with pytest.raises(CodecError):
            payload_from_dict({"data": {"tensor": {"shape": [3], "values": [1, 2]}}})
        with pytest.raises(CodecError):
            payload_from_dict({"rawTensor": {"dtype": "complex128", "data": ""}})
        # malformed inputs must be CodecError, never KeyError/binascii.Error
        with pytest.raises(CodecError):
            payload_from_dict({"rawTensor": {"dtype": "float32", "shape": [2]}})
        with pytest.raises(CodecError):
            payload_from_dict(
                {"rawTensor": {"dtype": "float32", "shape": [4], "data": base64.b64encode(b"\x00" * 8).decode()}}
            )
        with pytest.raises(CodecError):
            payload_from_dict({"binData": "!!!notb64"})
        with pytest.raises(CodecError):
            payload_from_dict({"meta": {"metrics": [{"key": "k", "type": "HISTOGRAM"}]}})

    def test_uint16_raw_not_confused_with_bfloat16(self):
        arr = np.array([1, 2, 3], dtype=np.uint16)
        p = Payload.from_array(arr, kind=DataKind.RAW)
        d = payload_to_dict(p)
        assert d["rawTensor"]["dtype"] == "uint16"
        p2 = payload_from_dict(d)
        assert p2.array.dtype == np.uint16
        np.testing.assert_array_equal(p2.array, arr)

    def test_raw_decode_is_writable(self):
        p = Payload.from_array(np.ones(3, dtype=np.float32), kind=DataKind.RAW)
        p2 = payload_from_dict(payload_to_dict(p))
        arr = p2.array
        arr += 1  # must not raise "read-only"
        np.testing.assert_array_equal(p2.array, [2.0, 2.0, 2.0])

    def test_mixed_type_ndarray_preserved(self):
        p = payload_from_dict({"data": {"ndarray": [["a", 1.5]]}})
        assert p.array.dtype == object
        out = payload_to_dict(p)["data"]["ndarray"]
        assert out == [["a", 1.5]]  # 1.5 stays a number, not "1.5"

    def test_meta_round_trip(self):
        msg = {
            "meta": {
                "puid": "x",
                "tags": {"a": 1, "b": "s"},
                "routing": {"router": 1},
                "requestPath": {"clf": "img:1"},
                "metrics": [{"key": "lat", "type": "TIMER", "value": 2.5}],
            }
        }
        p = payload_from_dict(msg)
        d = payload_to_dict(p)["meta"]
        assert d["routing"] == {"router": 1}
        assert d["requestPath"] == {"clf": "img:1"}
        assert d["metrics"][0]["key"] == "lat"


class TestProtoCodec:
    def test_tensor_round_trip(self):
        p = Payload.from_array(
            np.array([[0.5, 1.5]]), names=["a", "b"], kind=DataKind.TENSOR
        )
        p.meta.puid = "pp"
        p.meta.routing["r"] = 2
        msg = payload_to_proto(p)
        assert list(msg.data.tensor.shape) == [1, 2]
        p2 = payload_from_proto(msg)
        assert p2.meta.puid == "pp"
        assert p2.meta.routing == {"r": 2}
        np.testing.assert_array_equal(p2.array, [[0.5, 1.5]])

    def test_ndarray_round_trip(self):
        p = Payload.from_array(np.array([[1.0, 2.0]]), kind=DataKind.NDARRAY)
        p2 = payload_from_proto(payload_to_proto(p))
        assert p2.kind == DataKind.NDARRAY
        np.testing.assert_array_equal(p2.array, [[1.0, 2.0]])

    def test_raw_tensor_round_trip(self):
        arr = np.arange(4, dtype=np.int8)
        p = Payload.from_array(arr, kind=DataKind.RAW)
        p2 = payload_from_proto(payload_to_proto(p))
        assert p2.array.dtype == np.int8
        np.testing.assert_array_equal(p2.array, arr)

    def test_serialized_bytes_round_trip(self):
        p = Payload.from_array(np.ones((2, 2)), kind=DataKind.TENSOR)
        wire = payload_to_proto(p).SerializeToString()
        from seldon_core_tpu.proto import prediction_pb2 as pb

        msg = pb.SeldonMessage()
        msg.ParseFromString(wire)
        np.testing.assert_array_equal(payload_from_proto(msg).array, np.ones((2, 2)))


class TestFeedback:
    def test_round_trip(self):
        fb = feedback_from_dict(
            {
                "request": {"data": {"ndarray": [[1.0]]}},
                "response": {"meta": {"routing": {"ab": 1}}, "data": {"ndarray": [[0.9]]}},
                "reward": 1.0,
            }
        )
        assert fb.reward == 1.0
        assert fb.response.meta.routing == {"ab": 1}
        d = feedback_to_dict(fb)
        assert d["reward"] == 1.0
        assert d["request"]["data"]["ndarray"] == [[1.0]]


    def test_bad_reward_is_codec_error(self):
        with pytest.raises(CodecError):
            feedback_from_dict({"reward": "not-a-number"})


class TestParameters:
    def test_typed_parse(self):
        params = [
            {"name": "ratioA", "value": "0.5", "type": "FLOAT"},
            {"name": "n", "value": "3", "type": "INT"},
            {"name": "verbose", "value": "true", "type": "BOOL"},
            {"name": "label", "value": "x", "type": "STRING"},
        ]
        out = parse_parameters(params)
        assert out == {"ratioA": 0.5, "n": 3, "verbose": True, "label": "x"}

    def test_errors(self):
        with pytest.raises(ParameterError):
            parse_parameters([{"value": "1"}])
        with pytest.raises(ParameterError):
            parse_parameters([{"name": "x", "value": "1", "type": "TENSOR"}])
        with pytest.raises(ParameterError):
            parse_parameters([{"name": "x", "value": "abc", "type": "INT"}])

    def test_encode_inverse(self):
        src = {"a": 1, "b": 0.5, "c": True, "d": "s"}
        assert parse_parameters(encode_parameters(src)) == src
