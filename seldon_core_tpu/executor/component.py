"""Graph-unit adapter for compiled JAX models.

Makes a :class:`CompiledModel` (optionally behind a :class:`BatchQueue`) obey
the duck-typed component contract (``predict(X, names)``) so it slots into
any inference graph next to user Python components — the in-process
replacement for the reference's model-microservice pod behind ``/predict``
(reference: wrappers/python/model_microservice.py:23-84).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from seldon_core_tpu.executor.batcher import BatchQueue
from seldon_core_tpu.executor.compiled import CompiledModel
from seldon_core_tpu.graph.units import SeldonComponent


class JaxModelComponent(SeldonComponent):
    # metrics() returns cumulative queue gauges — safe to read concurrently;
    # without this opt-out the walker's annotation lock would serialize the
    # whole batching pipeline (see walker.make_annotation_lock)
    SAFE_ANNOTATIONS = True
    # a compiled forward is a pure function of its input: same tokens/rows
    # -> same scores, so the walker may serve exact repeats from the
    # response cache without a device step (docs/CACHING.md)
    DETERMINISTIC = True

    def __init__(
        self,
        model: CompiledModel,
        *,
        class_names: list[str] | None = None,
        batching: bool = True,
        max_batch: int = 64,
        max_delay_ms: float = 2.0,
        max_queue: int | None = None,
        warmup_example: np.ndarray | None = None,
    ):
        self.model = model
        self.warmup_example = warmup_example
        if class_names is not None:
            self.class_names = class_names
        self._queue = (
            BatchQueue(
                model, max_batch=max_batch, max_delay_ms=max_delay_ms,
                name=model.name, maxsize=max_queue,
            )
            if batching
            else None
        )
        if self._queue is not None and self._queue.flops_per_row is None:
            # feed the MFU gauge: ~2·params FLOPs per dense forward row
            # (roofline's estimate; exact XLA cost would need a re-compile)
            try:
                import jax

                self._queue.flops_per_row = 2.0 * sum(
                    int(np.prod(x.shape)) for x in jax.tree.leaves(model.params)
                )
            except Exception:
                pass

    def warmup(self) -> int:
        """Pre-compile every batch bucket; returns the program count.

        Serving gates readiness on this (reference's unwarmed engine shows a
        5,071 ms max-latency first-request spike, docs/benchmarking.md:42-45).
        """
        if self.warmup_example is None:
            return 0
        ex = np.asarray(self.warmup_example)
        return self.model.warmup(ex.shape[1:], ex.dtype)

    async def predict(self, X: np.ndarray, names: list[str]) -> np.ndarray:
        if self._queue is not None:
            return await self._queue.submit(np.asarray(X))
        return self.model(np.asarray(X))

    def metrics(self) -> list[dict[str, Any]]:
        if self._queue is None:
            return []
        # cumulative totals -> GAUGE: the metrics pipeline records custom
        # COUNTERs with inc(value) per request, which would sum running
        # totals quadratically
        return [
            {"key": f"{self.model.name}_device_steps", "type": "GAUGE", "value": self._queue.steps},
            {"key": f"{self.model.name}_device_rows", "type": "GAUGE", "value": self._queue.rows},
        ]

    async def close(self) -> None:
        if self._queue is not None:
            await self._queue.close()
