"""LLM graph plane (docs/GRAPHS.md): the inference graph as the unit of
value in the LLM era.

Three unit families ride the existing :class:`~seldon_core_tpu.graph.walker.
GraphWalker`:

* :class:`CascadeRouter` — FrugalGPT-style model cascades: the cheap tier
  answers first, an on-device confidence signal (mean top-2 logit margin,
  fetched with the tokens — zero extra host syncs) decides escalation to
  the next tier, gated by the request's remaining deadline budget.
* :class:`Guardrail` — pre/post policy stages declared in the CR: regex
  block, PII scrub, length/stop-token policy, pluggable classifier hook.
* The embeddings path (``POST /api/v0.1/embeddings``) lives on the
  generative unit itself (executor/generation.py ``embed_rows``); this
  package is graph-side only.
"""

from seldon_core_tpu.graphllm.cascade import CascadeRouter  # noqa: F401
from seldon_core_tpu.graphllm.guardrail import Guardrail  # noqa: F401

__all__ = ["CascadeRouter", "Guardrail"]
