"""pairing: acquire/release must pair on every path through a function.

Four ledgers keep the serving plane honest and each has a paired verb:

==============  =========================  ==========================
resource        acquire                    release
==============  =========================  ==========================
DeviceArbiter   ``.acquire(name)``         ``.release(name)``
MemoryManager   ``.reserve(owner, ...)``   ``.release(owner)``
AdapterPool     ``.acquire(adapter)``      ``.release_ref(idx)``
PrefixIndex     ``.acquire(tokens, ...)``  ``.release(tokens, ...)``
CircuitBreaker  ``.open(until)`` /         ``.close()`` /
                ``.probe_open()``          ``.probe_close()``
DrainGuard      ``.drain_begin()``         ``.drain_finish()``
==============  =========================  ==========================

A function that acquires one of these and has no matching release is a
leak on SOME path (the PR 10/12 bug class: an error branch between
reserve and release strands blocks/refs/bytes until restart).  Two
findings:

* ``missing release`` — the function never releases what it acquired.
  Ownership transfer (the release lives in a different function, e.g.
  ``reserve_for_prompt`` acquires what ``release_slot`` releases) is
  legitimate and annotated: ``# sct: pairing-ok <who releases and when>``.
* ``unprotected release`` — a release exists but only on the straight
  path: a ``raise``/``return`` between acquire and release can skip it
  and no release sits in a ``finally``/``except``.  Restructure with
  try/finally or annotate why the in-between code cannot raise.

Receivers are classified by name (``*arbiter*``/``*_arb*``,
``*memory*``/``host_memory()``, ``*lora_pool*``/``*adapter_pool*``,
``*prefix_index*``); a lock's ``.acquire()`` does not match.
"""

from __future__ import annotations

import ast
from typing import Iterable

from seldon_core_tpu.tools.sctlint.core import Context, Finding, Rule, dotted

# kind -> (receiver substrings, acquire verbs, release verbs)
KINDS = {
    "DeviceArbiter": (("arbiter", "_arb"), {"acquire"}, {"release"}),
    "MemoryManager": (("memory",), {"reserve"}, {"release"}),
    "AdapterPool": (("lora_pool", "adapter_pool"), {"acquire"},
                    {"release_ref"}),
    "PrefixIndex": (("prefix_index",), {"acquire"}, {"release"}),
    # chaos plane (docs/RESILIENCE.md): an opened breaker that no path
    # closes ejects a healthy replica forever; a drain that no path
    # finishes leaves admission paused until restart
    "CircuitBreaker": (("breaker",), {"open", "probe_open"},
                       {"close", "probe_close"}),
    "DrainGuard": (("sched", "scheduler"), {"drain_begin"},
                   {"drain_finish"}),
}


def _classify(call: ast.Call) -> tuple[str, str] | None:
    """(kind, 'acquire'|'release') for a tracked ledger call."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    recv = dotted(f.value).lower()
    if not recv:
        return None
    for kind, (substrs, acq, rel) in KINDS.items():
        if any(s in recv for s in substrs):
            if f.attr in acq:
                return kind, "acquire"
            if f.attr in rel:
                return kind, "release"
    return None


def _guard_raises(fn: ast.AST, acqs: list[ast.Call]) -> set[int]:
    """Raise lines inside an ``except`` handler whose ``try`` body
    contains one of the acquires: if that handler runs, the acquire
    itself failed and nothing is held, so the raise cannot leak."""
    acq_ids = {id(c) for c in acqs}
    out: set[int] = set()
    for n in ast.walk(fn):
        if not isinstance(n, ast.Try):
            continue
        if not any(
            id(sub) in acq_ids for s in n.body for sub in ast.walk(s)
        ):
            continue
        for h in n.handlers:
            for s in h.body:
                for sub in ast.walk(s):
                    if isinstance(sub, ast.Raise):
                        out.add(sub.lineno)
    return out


def _protected_lines(fn: ast.AST) -> set[int]:
    """Lines inside ``finally`` or ``except`` blocks: releases there run
    on the exceptional path too."""
    out: set[int] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Try):
            for h in n.handlers:
                for s in h.body:
                    for sub in ast.walk(s):
                        if hasattr(sub, "lineno"):
                            out.add(sub.lineno)
            for s in n.finalbody:
                for sub in ast.walk(s):
                    if hasattr(sub, "lineno"):
                        out.add(sub.lineno)
    return out


def check(ctx: Context) -> Iterable[Finding]:
    out: list[Finding] = []
    for src in ctx.py:
        if src.tree is None or "/tools/sctlint/" in src.rel:
            continue
        if src.rel.startswith("tests/"):
            continue
        for n in ast.walk(src.tree):
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            out.extend(_check_fn(src, n))
    return out


def _check_fn(src, fn) -> Iterable[Finding]:
    acquires: dict[str, list[ast.Call]] = {}
    releases: dict[str, list[ast.Call]] = {}
    # skip nested defs: they pair on their own (and closures that
    # acquire for a deferred release are ownership transfers anyway)
    def walk_no_nested(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and child is not fn:
                continue
            yield child
            yield from walk_no_nested(child)

    calls = [n for n in walk_no_nested(fn) if isinstance(n, ast.Call)]
    for call in calls:
        hit = _classify(call)
        if hit is None:
            continue
        kind, verb = hit
        (acquires if verb == "acquire" else releases).setdefault(
            kind, []
        ).append(call)

    out: list[Finding] = []
    protected = _protected_lines(fn)
    for kind, acqs in acquires.items():
        rels = releases.get(kind, [])
        own = KINDS[kind]
        release_names = "/".join(sorted(own[2]))
        if not rels:
            for call in acqs:
                out.append(Finding(
                    "pairing", src.rel, call.lineno,
                    f"{kind}.{call.func.attr}() has no matching "
                    f".{release_names}() in '{fn.name}' — leaked on "
                    "every path; pair it here or annotate the "
                    "ownership transfer",
                    src.snippet(call.lineno),
                ))
            continue
        # release exists: is any protected, or can an early exit skip it?
        if any(r.lineno in protected for r in rels):
            continue
        first_acq = min(c.lineno for c in acqs)
        last_rel = max(r.lineno for r in rels)
        guard = _guard_raises(fn, acqs)
        escapes = [
            n for n in ast.walk(fn)
            if isinstance(n, (ast.Raise, ast.Return))
            and first_acq < n.lineno < last_rel
            and n.lineno not in guard
        ]
        if escapes:
            out.append(Finding(
                "pairing", src.rel, first_acq,
                f"{kind} release at line {last_rel} of '{fn.name}' can "
                f"be skipped by the raise/return at line "
                f"{escapes[0].lineno} — move the release into a "
                "finally (or annotate why the branch releases "
                "elsewhere)",
                src.snippet(first_acq),
            ))
    return out


RULE = Rule(
    id="pairing",
    summary="ledger acquire/release pair on every path",
    explain=__doc__,
    check=check,
)
