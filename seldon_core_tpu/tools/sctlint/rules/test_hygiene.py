"""test-hygiene: tier-1 scope is exactly what the verify command selects.

ROADMAP's tier-1 command runs ``pytest -m 'not slow'`` with
``JAX_PLATFORMS=cpu``.  That contract only holds if the ``slow`` marker
is complete: a test that spawns subprocesses, drives real sockets for
minutes, or needs non-CPU devices must carry it — otherwise tier-1
inherits a flaky multi-minute e2e, and the seed count stops meaning
anything.

A test function is **non-tier-1-safe** when its body (or a module-level
helper it calls) does any of:

* ``subprocess.Popen`` / ``run`` / ``check_*`` / ``call`` — spawned
  servers and worker processes;
* ``jax.distributed.initialize`` — multi-process mesh formation;
* ``jax.devices("tpu")`` — a hard device requirement.

Such a test must be marked ``slow`` (function, class, or module
``pytestmark``) or annotated ``# sct: test-hygiene-ok <reason>`` (e.g.
a sub-second one-shot build step).  The inverse audit — ``slow`` on a
test with none of the signals — is deliberately NOT flagged: slowness
has more causes than this rule can see.
"""

from __future__ import annotations

import ast
from typing import Iterable

from seldon_core_tpu.tools.sctlint.core import Context, Finding, Rule, dotted

_SUBPROCESS = (
    "subprocess.Popen", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
)


def _has_slow(deco_list) -> bool:
    for d in deco_list:
        name = dotted(d if not isinstance(d, ast.Call) else d.func)
        if name.endswith("mark.slow") or name == "slow":
            return True
    return False


def _module_marked_slow(tree: ast.Module) -> bool:
    for n in tree.body:
        if isinstance(n, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "pytestmark"
            for t in n.targets
        ):
            for sub in ast.walk(n.value):
                if isinstance(sub, ast.Attribute) and sub.attr == "slow":
                    return True
    return False


def _signals(node: ast.AST) -> list[tuple[int, str]]:
    out = []
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        d = dotted(n.func)
        if d in _SUBPROCESS:
            out.append((n.lineno, d))
        elif d == "jax.distributed.initialize":
            out.append((n.lineno, d))
        elif d == "jax.devices" and n.args \
                and isinstance(n.args[0], ast.Constant) \
                and n.args[0].value == "tpu":
            out.append((n.lineno, 'jax.devices("tpu")'))
    return out


def check(ctx: Context) -> Iterable[Finding]:
    out: list[Finding] = []
    for src in ctx.py:
        if src.tree is None or not src.rel.startswith("tests/"):
            continue
        if _module_marked_slow(src.tree):
            continue
        # module-level helpers a test may call: name -> signal list
        helpers: dict[str, list[tuple[int, str]]] = {}
        for n in src.tree.body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and not n.name.startswith("test"):
                helpers[n.name] = _signals(n)

        def fn_signals(fn) -> list[tuple[int, str]]:
            sig = _signals(fn)
            for c in ast.walk(fn):
                if isinstance(c, ast.Call):
                    d = dotted(c.func)
                    bare = d.rsplit(".", 1)[-1]
                    if bare in helpers and helpers[bare]:
                        sig.append((c.lineno, f"{bare}() -> "
                                    f"{helpers[bare][0][1]}"))
            return sig

        def visit(body, class_slow: bool, methods: dict):
            for n in body:
                if isinstance(n, ast.ClassDef):
                    own_methods = {
                        m.name: m for m in n.body
                        if isinstance(m, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                    }
                    visit(n.body, class_slow or _has_slow(n.decorator_list),
                          own_methods)
                elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and n.name.startswith("test"):
                    if class_slow or _has_slow(n.decorator_list):
                        continue
                    sig = fn_signals(n)
                    # class-local helpers (self._launch style)
                    for c in ast.walk(n):
                        if isinstance(c, ast.Call) \
                                and isinstance(c.func, ast.Attribute) \
                                and c.func.attr in methods \
                                and c.func.attr != n.name:
                            hsig = _signals(methods[c.func.attr])
                            if hsig:
                                sig.append((c.lineno,
                                            f"self.{c.func.attr}() -> "
                                            f"{hsig[0][1]}"))
                    if sig:
                        line, what = sig[0]
                        out.append(Finding(
                            "test-hygiene", src.rel, n.lineno,
                            f"test '{n.name}' is not tier-1-safe "
                            f"({what} at line {line}) but carries no "
                            "'slow' marker — mark it or annotate why "
                            "it is cheap",
                            src.snippet(n.lineno),
                        ))
        visit(src.tree.body, False, {})
    return out


RULE = Rule(
    id="test-hygiene",
    summary="non-tier-1-safe tests carry the slow marker",
    explain=__doc__,
    check=check,
)
