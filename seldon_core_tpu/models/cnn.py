"""deep_mnist-style convnet (the reference's TF example,
reference: examples/models/deep_mnist/) rebuilt in Flax: two conv+pool
blocks, one dense layer, softmax head.  Accepts flat 784 rows (the wire
format the reference example used) or NHWC images."""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp

from seldon_core_tpu.models.common import annotate_params


@dataclasses.dataclass(frozen=True)
class Config:
    image_size: int = 28
    channels: int = 1
    n_classes: int = 10
    hidden: int = 1024


class CNN(nn.Module):
    cfg: Config

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        if x.ndim == 2:  # flat rows off the wire
            x = x.reshape((-1, c.image_size, c.image_size, c.channels))
        x = nn.Conv(32, (5, 5), padding="SAME", name="conv1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", name="conv2")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(c.hidden, name="fc1")(x))
        x = nn.Dense(c.n_classes, name="head")(x)
        return nn.softmax(x)


def init_params(rng: jax.Array, cfg: Config = Config()):
    x = jnp.zeros((1, cfg.image_size, cfg.image_size, cfg.channels), jnp.float32)
    return CNN(cfg).init(rng, x)


def apply(params, batch, cfg: Config = Config()):
    return CNN(cfg).apply(params, batch)


_AXIS_RULES = [
    (r"conv\d+/kernel", (None, None, None, "conv_out")),
    (r"conv\d+/bias", ("conv_out",)),
    (r"fc1/kernel", ("embed", "mlp")),
    (r"fc1/bias", ("mlp",)),
    (r"head/kernel", ("mlp", None)),
    (r"head/bias", None),
]


def param_logical_axes(params):
    return annotate_params(params, _AXIS_RULES)
