"""Disaggregated prefill/decode serving (docs/DISAGGREGATION.md).

DistServe/Splitwise-style pool split: an engine boots as ``prefill``,
``decode``, or ``unified`` (``SCT_ENGINE_ROLE`` env, or the operator's
``seldon.io/engine-role`` annotation injecting it).  A prefill engine runs
bucketed prefill and exports the resulting paged-KV blocks + sampling
carry over the versioned length-prefixed JSON + raw-ndarray framing the
multihost control plane speaks (executor/multihost.py); a decode engine
imports them into its own paged pool and admits the slot at the next sync
point of the overlapped scheduler.  A failed handoff falls back to
unified-mode local decode on the sender and leaks nothing — the exported
blocks stay pinned to the sending slot until the engine releases them.

The gateway side (disagg/router.py) routes across multi-upstream
deployment records: longest-prefix match against polled per-replica prefix
digests first, power-of-two-choices on queue-wait EWMA otherwise.
"""

from __future__ import annotations

import os

ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_UNIFIED = "unified"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_UNIFIED)

ROLE_ENV = "SCT_ENGINE_ROLE"
DECODE_UPSTREAMS_ENV = "SCT_DISAGG_DECODE"


def resolve_role(value: str | None = None, environ: dict | None = None) -> str:
    """Engine role: explicit ``value`` wins, then ``SCT_ENGINE_ROLE``, then
    unified.  An unknown role is a boot-time ValueError — a typo'd role
    must never silently serve as a unified engine inside a split pool."""
    env = environ if environ is not None else os.environ
    role = (value or env.get(ROLE_ENV, "") or ROLE_UNIFIED).strip().lower()
    if role not in ROLES:
        raise ValueError(
            f"engine role {role!r} is not one of {', '.join(ROLES)}"
        )
    return role


def decode_upstreams(value: str | None = None, environ: dict | None = None) -> list[str]:
    """The prefill pool's decode peers: ``SCT_DISAGG_DECODE`` is a
    comma-separated ``host:port`` list (REST ports)."""
    env = environ if environ is not None else os.environ
    raw = value if value is not None else env.get(DECODE_UPSTREAMS_ENV, "")
    return [u.strip() for u in raw.split(",") if u.strip()]


from seldon_core_tpu.disagg.handoff import (  # noqa: E402
    HANDOFF_KEY,
    HandoffError,
    decode_handoff,
    encode_handoff,
)
from seldon_core_tpu.disagg.router import (  # noqa: E402
    ReplicaRouter,
    RouterPoller,
    extract_prompt_tokens,
    prompt_chain_hashes,
)

__all__ = [
    "ROLE_PREFILL",
    "ROLE_DECODE",
    "ROLE_UNIFIED",
    "ROLES",
    "ROLE_ENV",
    "DECODE_UPSTREAMS_ENV",
    "resolve_role",
    "decode_upstreams",
    "HANDOFF_KEY",
    "HandoffError",
    "encode_handoff",
    "decode_handoff",
    "ReplicaRouter",
    "RouterPoller",
    "extract_prompt_tokens",
    "prompt_chain_hashes",
]
