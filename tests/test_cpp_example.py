"""The C++ example must compile and serve through a real engine graph —
polyglot parity is a contract claim, so it gets an executable proof."""

import json
import os
import shutil
import subprocess
import time
import urllib.request

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CPP_DIR = os.path.join(REPO_ROOT, "examples", "cpp-model")


@pytest.mark.slow
def test_cpp_model_through_engine(tmp_path):
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ in environment")
    binary = str(tmp_path / "model_server")
    subprocess.run(
        [gxx, "-O2", "-std=c++17", "-o", binary,
         os.path.join(CPP_DIR, "model_server.cpp")],
        check=True,
    )
    env = dict(os.environ)
    env["PREDICTIVE_UNIT_SERVICE_PORT"] = "19911"
    cpp = subprocess.Popen([binary], env=env)
    engine = None
    try:
        # direct contract check
        body = json.dumps({"data": {"ndarray": [[6.1, 2.8, 4.7, 1.2]]}}).encode()
        deadline = time.time() + 30
        while True:
            try:
                req = urllib.request.Request(
                    "http://127.0.0.1:19911/predict", body,
                    {"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=5) as resp:
                    direct = json.loads(resp.read())
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.2)
        probs = direct["data"]["ndarray"][0]
        assert len(probs) == 3 and abs(sum(probs) - 1.0) < 1e-6

        # through an engine graph (remote REST unit)
        import base64
        import sys

        predictor = {
            "name": "p",
            "graph": {
                "name": "cpp-clf", "type": "MODEL",
                "endpoint": {"service_host": "127.0.0.1",
                             "service_port": 19911, "type": "REST"},
            },
        }
        eng_env = dict(os.environ)
        eng_env["ENGINE_PREDICTOR"] = base64.b64encode(
            json.dumps(predictor).encode()
        ).decode()
        eng_env["JAX_PLATFORMS"] = "cpu"
        eng_env["ENGINE_GRPC_OPTIONAL"] = "1"
        engine = subprocess.Popen(
            [sys.executable, "-m", "seldon_core_tpu.engine.app",
             "--port", "19912", "--grpc-port", "19913"],
            env=eng_env,
        )
        deadline = time.time() + 60
        while True:
            try:
                req = urllib.request.Request(
                    "http://127.0.0.1:19912/api/v0.1/predictions", body,
                    {"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=5) as resp:
                    out = json.loads(resp.read())
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.5)
        assert out["status"]["code"] == 200
        assert out["data"]["ndarray"][0] == pytest.approx(probs)
        assert "cpp-clf" in out["meta"]["requestPath"]
    finally:
        cpp.terminate()
        cpp.wait(timeout=10)
        if engine is not None:
            engine.terminate()
            engine.wait(timeout=10)
