"""In-memory request/response payload for the data plane.

The reference carries ``SeldonMessage`` protobufs (or their JSON encoding)
through every layer and re-parses them at each hop (reference:
engine/.../api/rest/RestClientController.java:108-110, apife forwards the raw
JSON string).  Here the wire formats (JSON / proto / raw tensor) are decoded
exactly once at the boundary into :class:`Payload` — a thin record holding a
numpy array (or bytes / str) plus metadata — and the whole graph walk operates
on it zero-copy.  Device transfer happens only inside the executor.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any

import numpy as np


class DataKind(enum.Enum):
    """Which member of the SeldonMessage data oneof the payload came from.

    Preserved across graph nodes so the response is encoded the same way the
    request was (the reference preserves ndarray-vs-tensor encoding too,
    reference: engine/.../predictors/PredictorUtils.java:107-127).
    """

    TENSOR = "tensor"
    NDARRAY = "ndarray"
    RAW = "rawTensor"
    BINARY = "binData"
    STRING = "strData"
    EMPTY = "empty"


@dataclasses.dataclass
class Metric:
    """A custom metric emitted by user model code."""

    key: str
    type: str = "COUNTER"  # COUNTER | GAUGE | TIMER
    value: float = 0.0


@dataclasses.dataclass
class Meta:
    """Request metadata threaded through the graph.

    ``puid`` correlates a request end-to-end (reference:
    engine/.../service/PredictionService.java:52-58); ``routing`` records the
    child index each router chose, which the feedback walk replays
    (reference: engine/.../predictors/PredictiveUnitBean.java:126-168);
    ``tags`` are merged across every node's response.
    """

    puid: str = ""
    tags: dict[str, Any] = dataclasses.field(default_factory=dict)
    routing: dict[str, int] = dataclasses.field(default_factory=dict)
    request_path: dict[str, str] = dataclasses.field(default_factory=dict)
    metrics: list[Metric] = dataclasses.field(default_factory=list)

    def merge_from(self, other: "Meta") -> None:
        """Merge another node's response meta into this one."""
        if other.puid:
            self.puid = other.puid
        self.tags.update(other.tags)
        self.routing.update(other.routing)
        self.request_path.update(other.request_path)
        self.metrics.extend(other.metrics)


@dataclasses.dataclass
class Payload:
    """The unit of data flowing through the inference graph."""

    data: np.ndarray | bytes | str | None = None
    names: list[str] = dataclasses.field(default_factory=list)
    kind: DataKind = DataKind.EMPTY
    meta: Meta = dataclasses.field(default_factory=Meta)

    @classmethod
    def from_array(
        cls,
        array: np.ndarray,
        names: list[str] | None = None,
        kind: DataKind = DataKind.NDARRAY,
        meta: Meta | None = None,
    ) -> "Payload":
        return cls(
            data=np.asarray(array),
            names=list(names or []),
            kind=kind,
            meta=meta or Meta(),
        )

    @property
    def array(self) -> np.ndarray:
        """The numeric payload; raises if this payload is not numeric."""
        if not isinstance(self.data, np.ndarray):
            raise TypeError(
                f"payload holds {self.kind.value!r} data, not a numeric array"
            )
        return self.data

    def is_numeric(self) -> bool:
        return isinstance(self.data, np.ndarray)

    def with_array(self, array: np.ndarray, names: list[str] | None = None) -> "Payload":
        """A new payload with replaced numeric data, preserving encoding+meta."""
        kind = self.kind
        if kind in (DataKind.BINARY, DataKind.STRING, DataKind.EMPTY):
            kind = DataKind.NDARRAY
        return Payload(
            data=np.asarray(array),
            names=list(names) if names is not None else list(self.names),
            kind=kind,
            meta=self.meta,
        )


@dataclasses.dataclass
class FeedbackPayload:
    """A reward signal for the feedback walk (reference: proto/prediction.proto
    ``Feedback{request, response, reward, truth}``)."""

    request: Payload | None = None
    response: Payload | None = None
    reward: float = 0.0
    truth: Payload | None = None
